"""Fleet discovery at the front door: replica auto-registration,
push-based telemetry to the router, and the observability-fed half of
placement.

Before this module the router fronted a *static* ``--replicas`` list
that it *polled* — scaling the fleet meant restarting the front door,
and placement saw only what lite health carries. This module inverts
the direction with the machinery PR 11 already built for the SPMD
coordinator (obs/federation.py over the utils/wire.py length-prefixed,
token-gated framing):

  * **ReplicaAnnouncer** (replica side) — a thin configuration of
    ``TelemetryExporter`` pointed at the router instead of the
    coordinator. Every ``interval_s`` it ships one frame whose
    ``health`` section is a lite-health SUPERSET (queue depths by
    class, config epoch + switch_in_flight, draining + drain ETA, SLO
    attainment) extended with KV-pool headroom lifted from the
    existing page gauges and the replica's active sentinel anomalies;
    a bounded slice of the local metrics registry rides along for the
    router's replica-labeled federated /metrics. ``depart()`` ships an
    explicit departure notice (``departing: true``) so shutdown is an
    announcement, not an inference from silence.

  * **AnnounceListener** (router side) — a ``TelemetryCollector``
    subclass: same token-gated hello, bounded frames, per-origin
    views, min-over-frames clock offsets and federated render; it
    overrides the ingest hook to drive fleet membership and the
    exposition label so federated families carry ``replica=`` (the
    front door's dimension) instead of ``host=``.

  * **FleetDiscovery** — the glue onto the router's existing organs.
    A replica's FIRST frame registers it (tracker + deterministic ring
    position — a rejoin lands on exactly its old vnodes, so a
    depart+rejoin cycle moves ~1/N of keys once, not twice). Every
    frame refreshes liveness through ``tracker.note_ok(push=True)``,
    which suppresses the redundant poll while frames are fresh and
    FALLS BACK to the existing poll path the moment they stop — no
    mode switch anywhere. Frames also feed placement: pool-headroom
    and worst-class-attainment become multiplicative ``RoutingPolicy``
    factors (0.05 floor, per-factor provenance — the PR 16 anomaly-
    weight audit discipline). A departure notice starts
    drain-then-forget: the replica stops admitting NEW work instantly
    (``ReplicaState.departing``), keeps serving sticky attaches, and
    is forgotten — tracker, ring, weights, view — once its reported
    load reaches zero (or a grace deadline, for a replica that died
    mid-drain). Membership churn publishes typed
    ``replica_joined`` / ``replica_departed`` / ``replica_stale``
    events on the router's event ring, so discovery shows up in the
    same timelines as everything else.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from cake_tpu.obs import metrics as _m
from cake_tpu.obs.federation import (_HostView, TelemetryCollector,
                                     TelemetryExporter)
from cake_tpu.obs.metrics import _escape_label_value

log = logging.getLogger(__name__)

# the bounded slice of a replica's registry that rides each announce
# frame — exactly the families placement and the fleet view read, not
# the whole registry (the router federates these replica-labeled; a
# full dump would grow the router's /metrics with every family every
# replica owns)
ANNOUNCE_METRIC_PREFIXES: Tuple[str, ...] = (
    "cake_engine_kv_pages",      # pool headroom (total/free)
    "cake_device_hbm_",          # device memory, the fleet view's column
    "cake_kv_pool_",             # pool byte gauges where present
    "cake_slo_attainment",       # per-class attainment windows
)

_ANNOUNCE_FRAMES = _m.counter(
    "cake_router_announce_frames_total",
    "Announce/telemetry frames the router ingested from each replica "
    "(router/discovery.py; the push path that supersedes polling while "
    "fresh)", labelnames=("replica",))
_ANNOUNCE_DEPARTURES = _m.counter(
    "cake_router_announce_departures_total",
    "Explicit departure notices received, by replica — each starts the "
    "drain-then-forget sequence", labelnames=("replica",))
_FLEET_REPLICAS = _m.gauge(
    "cake_router_fleet_replicas",
    "Replicas currently tracked by the router, by how they entered the "
    "fleet (static = --replicas seed, announced = self-registered)",
    labelnames=("source",))
_FLEET_WEIGHT = _m.gauge(
    "cake_router_fleet_weight",
    "Composed placement weight per replica (product of anomaly/"
    "headroom/attainment factors, 0.05 floor; 1 = unweighted — see "
    "GET /api/v1/fleet for per-factor provenance)",
    labelnames=("replica",))
_FLEET_STALE = _m.counter(
    "cake_router_fleet_stale_total",
    "Announce streams that went quiet past the staleness window, by "
    "replica — each transition falls placement back to the poll path",
    labelnames=("replica",))


def _gauge_value(name: str) -> Optional[float]:
    """First sample of a local registry gauge, or None. The announcer
    reads the page gauges BACK from the registry instead of touching
    engine internals — the gauge refresh already holds the engine's
    locking discipline (non-blocking switch-lock acquire)."""
    fam = _m.REGISTRY.get(name)
    if fam is None:
        return None
    return next(iter(fam.samples().values()), None)


class ReplicaAnnouncer:
    """Replica-side announce stream: a TelemetryExporter pointed at the
    router's AnnounceListener.

    ``replica`` is the replica's OWN serving address ("host:port") —
    it is both the fleet identity and the address the router proxies
    to, so it must be reachable from the router. ``health`` supplies
    the lite health doc (api/server.py ``health(lite=True)``);
    ``engine`` is optional and adds pool headroom + active sentinel
    anomalies to each frame. Everything is best-effort: a raising
    supplier drops its enrichment, never the frame, and a dead router
    degrades to counted send errors + reconnects (telemetry must never
    fail serving)."""

    # cakelint guards discipline: the engine (and its sentinel) and
    # the health supplier are optional planes — an engine-less replica
    # still announces liveness
    OPTIONAL_PLANES = ("_engine", "_sentinel", "_health")

    def __init__(self, router_address: str, replica: str,
                 token: Optional[str] = None,
                 interval_s: float = 2.0, *,
                 health=None, engine=None,
                 registry: Optional[_m.Registry] = None,
                 metric_prefixes: Tuple[str, ...]
                 = ANNOUNCE_METRIC_PREFIXES,
                 connect_timeout_s: float = 10.0,
                 start: bool = True):
        self.replica = str(replica)
        self._health = health
        self._engine = engine
        self._sentinel = (getattr(engine, "sentinel", None)
                          if engine is not None else None)
        self._registry = registry
        self._prefixes = tuple(metric_prefixes)
        self._departing = False
        self._exporter = TelemetryExporter(
            router_address, host=self.replica, token=token,
            interval_s=interval_s, registry=registry,
            metric_prefixes=self._prefixes, events=None,
            health_snapshot=self._announce_doc,
            connect_timeout_s=connect_timeout_s, start=start)

    @property
    def frames_sent(self) -> int:
        return self._exporter.frames_sent

    @property
    def interval_s(self) -> float:
        return self._exporter._interval

    def start(self) -> "ReplicaAnnouncer":
        self._exporter.start()
        return self

    # -- frame content ----------------------------------------------------

    def _announce_doc(self) -> Dict:
        """The frame's ``health`` section: the lite health doc extended
        with pool headroom, active sentinel anomalies, and the
        departure flag."""
        doc: Dict = {}
        if self._health is not None:
            try:
                doc = dict(self._health() or {})
            except Exception:  # noqa: BLE001 — a raising supplier drops
                log.debug("announce health supplier failed",  # its section
                          exc_info=True)
                doc = {}
        doc.setdefault("status", "ok")
        doc.setdefault("replica", self.replica)
        doc.setdefault("now", time.time())
        if self._engine is not None:
            try:
                from cake_tpu.obs.steps import refresh_page_gauges
                refresh_page_gauges(self._engine)
                total = _gauge_value("cake_engine_kv_pages_total")
                free = _gauge_value("cake_engine_kv_pages_free")
                if total:
                    doc["pool"] = {"pages_total": int(total),
                                   "pages_free": int(free or 0)}
            except Exception:  # noqa: BLE001
                log.debug("announce pool enrichment failed",
                          exc_info=True)
        if self._sentinel is not None:
            try:
                active = self._sentinel.state(limit=0).get("active", ())
                doc["anomalies"] = sorted(
                    {a.get("kind") for a in active if a.get("kind")})
            except Exception:  # noqa: BLE001
                log.debug("announce sentinel enrichment failed",
                          exc_info=True)
        if self._departing:
            doc["departing"] = True
        return doc

    # -- lifecycle --------------------------------------------------------

    def flush(self, connect_timeout_s: Optional[float] = None) -> bool:
        return self._exporter.flush(connect_timeout_s)

    def depart(self, timeout_s: float = 2.0) -> bool:
        """Ship the departure notice NOW (synchronous, bounded budget).
        Called at the TOP of shutdown — before the drain begins — so
        the router stops admitting new work here while in-flight
        streams finish. False = the notice did not go out (the router
        will infer departure from staleness instead)."""
        self._departing = True
        try:
            return self._exporter.flush(connect_timeout_s=timeout_s,
                                        _ignore_stop=True)
        except Exception:  # noqa: BLE001 — shutdown must proceed
            return False

    def close(self, depart: bool = True) -> None:
        """Stop announcing; by default the terminal frame (the exporter
        close-flush) carries the departure notice."""
        if depart:
            self._departing = True
        self._exporter.close(flush=True)


class AnnounceListener(TelemetryCollector):
    """Router-side announce endpoint: the TelemetryCollector accept/
    hello/ingest machinery with (a) the ingest hook driving fleet
    membership through the owning FleetDiscovery and (b) the federated
    exposition label renamed ``host`` -> ``replica`` — the front
    door's dimension, matching every other cake_router_* family."""

    def __init__(self, discovery: "FleetDiscovery", host: str = "",
                 port: int = 0, token: Optional[str] = None, *,
                 stale_after_s: float = 10.0, max_replicas: int = 64):
        # set BEFORE super().__init__: the accept thread starts inside
        # it, and a fast replica's first frame must find the hook
        self._discovery = discovery
        super().__init__(host=host, port=port, token=token,
                         local_host="router",
                         stale_after_s=stale_after_s,
                         max_hosts=max_replicas)

    def _ingest(self, host: str, payload: bytes) -> None:
        with self._lock:
            if host not in self._views:
                # the view was popped by forget() while this replica's
                # connection stayed open (frames raced the departure,
                # or it cancelled its shutdown): recreate it — the
                # connection already passed the token gate at hello
                if len(self._views) >= self._max_hosts:
                    return
                self._views[host] = _HostView(
                    host, self._event_ring, "(reannounced)")
        super()._ingest(host, payload)
        with self._lock:
            view = self._views.get(host)
            doc = (dict(view.health)
                   if view is not None and isinstance(view.health, dict)
                   else {})
            offset = view.offset if view is not None else None
        self._discovery.on_frame(host, doc, offset)

    def forget(self, replica: str) -> None:
        """Drop a forgotten replica's view so its federated families
        stop rendering and a rejoin starts from a clean slate."""
        with self._lock:
            self._views.pop(replica, None)

    def hbm_for(self, replica: str) -> Dict[str, Dict]:
        """Per-device HBM gauges lifted from the replica's shipped
        metric dump — the fleet view's memory column."""
        with self._lock:
            view = self._views.get(replica)
            metrics = list(view.metrics) if view is not None else []
        return self._hbm_from_metrics(metrics)

    @staticmethod
    def _suffix(labels: List[str], values, host: str,
                extra: Tuple = ()) -> str:
        pairs = list(zip(labels, [str(v) for v in values]))
        pairs.append(("replica", host))
        pairs.extend(extra)
        body = ",".join('%s="%s"' % (k, _escape_label_value(v))
                        for k, v in pairs)
        return "{" + body + "}"


class FleetDiscovery:
    """The router's discovery plane: owns the AnnounceListener, maps
    frames onto tracker/ring/policy, and runs the maintenance loop
    (stale transitions, drain-then-forget, fleet gauges).

    Placement factors (the observability-fed half of routing): each
    frame recomputes two multiplicative RoutingPolicy factors with
    provenance —

      headroom    free/total KV pool pages; 1.0 above
                  ``HEADROOM_LOW_FRAC`` free, then linear down (a
                  nearly-full pool reads as nearly-saturated)
      attainment  worst per-class attainment_1m; 1.0 at or above
                  ``ATTAINMENT_LOW``, then linear down (a replica
                  missing its SLOs stops attracting new load before
                  it starts shedding)

    both floored at 0.05 by the policy — de-weighting never becomes a
    de-facto ejection. switch_in_flight routing is NOT a factor: the
    policy routes around the flag directly (router/policy.py
    ``_eligible``) and restores the replica the moment a doc shows the
    epoch landed."""

    # cakelint guards discipline: the maintenance thread exists only
    # between start() and close(); the router's event ring is optional
    # (--event-ring 0)
    OPTIONAL_PLANES = ("_thread",)

    HEADROOM_LOW_FRAC = 0.25
    ATTAINMENT_LOW = 0.9

    def __init__(self, router, address: str = "127.0.0.1:0",
                 token: Optional[str] = None, *,
                 announce_interval_s: float = 2.0,
                 stale_after_s: Optional[float] = None,
                 forget_grace_s: float = 30.0,
                 max_replicas: int = 64, start: bool = False):
        if announce_interval_s <= 0:
            raise ValueError(
                f"announce_interval_s {announce_interval_s} must be > 0")
        host, _, port = str(address).rpartition(":")
        self.router = router
        self.announce_interval_s = float(announce_interval_s)
        # quiet = three missed announce intervals (never tighter than
        # the tracker's own poll-staleness window)
        self.stale_after_s = (
            float(stale_after_s) if stale_after_s is not None
            else max(3.0 * self.announce_interval_s,
                     router.tracker.stale_after_s))
        self.forget_grace_s = float(forget_grace_s)
        self._mu = threading.Lock()   # serializes membership changes
        self._stale: set = set()      # replicas currently fallen to poll
        self._depart_deadline: Dict[str, float] = {}
        self.listener = AnnounceListener(
            self, host=host or "", port=int(port or 0), token=token,
            stale_after_s=self.stale_after_s,
            max_replicas=max_replicas)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    @property
    def port(self) -> int:
        return self.listener.port

    # -- frame ingestion (listener threads) --------------------------------

    def on_frame(self, replica: str, doc: Dict,
                 offset: Optional[float]) -> None:
        """One announce frame arrived. Registration, departure, rejoin
        and liveness all flow through here; membership changes are
        serialized under _mu (one listener thread per replica)."""
        if not doc:
            return   # liveness-only frame with no health yet: ignore
        tracker = self.router.tracker
        departing = bool(doc.get("departing"))
        now = time.monotonic()
        with self._mu:
            st = tracker.get(replica)
            if st is None:
                if departing:
                    return   # a goodbye from a replica we never knew
                if ":" not in replica:
                    # the announced name IS the proxy target — an
                    # unroutable name would poison the ring
                    log.warning("discovery: ignoring announce from "
                                "unroutable replica id %r", replica)
                    return
                tracker.add(replica, source="announced")
                self.router.ring.add(replica)
                self._publish("replica_joined", replica=replica,
                              source="announced")
                log.info("discovery: replica %s joined via announce",
                         replica)
            elif departing and not st.departing:
                st.departing = True
                self._depart_deadline[replica] = (
                    now + self.forget_grace_s)
                _ANNOUNCE_DEPARTURES.labels(replica=replica).inc()
                self._publish("replica_departed", replica=replica,
                              source=st.source, load=st.load)
                log.info("discovery: replica %s departing (load=%d) — "
                         "draining then forgetting", replica, st.load)
            elif not departing and st.departing:
                # it came back before being forgotten (a cancelled
                # shutdown / flap): same tracker entry, same ring
                # vnodes — never a double-register
                st.departing = False
                self._depart_deadline.pop(replica, None)
                self._publish("replica_joined", replica=replica,
                              source=st.source, rejoined=True)
            # a fresh frame ends any stale episode
            self._stale.discard(replica)
        tracker.note_ok(replica, doc, push=True)
        _ANNOUNCE_FRAMES.labels(replica=replica).inc()
        self._apply_factors(replica, doc)

    def _apply_factors(self, replica: str, doc: Dict) -> None:
        policy = self.router.policy
        pool = doc.get("pool") or {}
        total, free = pool.get("pages_total"), pool.get("pages_free")
        w, cause = 1.0, None
        if (isinstance(total, (int, float)) and total > 0
                and isinstance(free, (int, float))):
            frac = max(0.0, float(free) / float(total))
            if frac < self.HEADROOM_LOW_FRAC:
                w = frac / self.HEADROOM_LOW_FRAC
                cause = (f"pool free fraction {frac:.3f} < "
                         f"{self.HEADROOM_LOW_FRAC}")
        policy.set_factor(replica, "headroom", w, cause=cause)
        att = (doc.get("slo") or {}).get("attainment_1m") or {}
        w, cause = 1.0, None
        vals = [v for v in att.values() if isinstance(v, (int, float))]
        if vals:
            worst = min(vals)
            if worst < self.ATTAINMENT_LOW:
                w = max(0.0, float(worst)) / self.ATTAINMENT_LOW
                cause = (f"worst-class attainment_1m {worst:.3f} < "
                         f"{self.ATTAINMENT_LOW}")
        policy.set_factor(replica, "attainment", w, cause=cause)
        _FLEET_WEIGHT.labels(replica=replica).set(
            round(policy.weight(replica), 4))

    def _publish(self, type: str, **fields) -> None:
        if self.router.events is not None:
            try:
                self.router.events.publish(type, **fields)
            except Exception:  # noqa: BLE001 — telemetry never takes
                log.debug("discovery event publish failed",  # us down
                          exc_info=True)

    # -- maintenance (stale transitions + drain-then-forget) ---------------

    def maintain(self, now: Optional[float] = None) -> None:
        """One maintenance pass (the synchronous seam; start() runs it
        on a daemon thread). Detects announce streams gone quiet
        (publish replica_stale once per transition — polling has
        already resumed automatically via the aged-out push stamp),
        forgets drained departures, and reaps announced replicas that
        died without a goodbye (ejected + quiet past the grace
        window)."""
        now = time.monotonic() if now is None else now
        tracker = self.router.tracker
        with self._mu:
            for st in tracker.states():
                if st.last_push is None:
                    continue   # poll-only replica: nothing pushed yet
                quiet_s = now - st.last_push
                if quiet_s > self.stale_after_s:
                    if st.name not in self._stale and not st.departing:
                        self._stale.add(st.name)
                        _FLEET_STALE.labels(replica=st.name).inc()
                        self._publish("replica_stale", replica=st.name,
                                      age_s=round(quiet_s, 3))
                        log.warning(
                            "discovery: replica %s announce stream "
                            "quiet for %.1fs — falling back to polling",
                            st.name, quiet_s)
                else:
                    self._stale.discard(st.name)
                if st.departing:
                    deadline = self._depart_deadline.get(
                        st.name, now + self.forget_grace_s)
                    if st.load <= 0 or now >= deadline:
                        self._forget(st.name)
                elif (st.source == "announced" and st.ejected
                      and quiet_s > self.stale_after_s
                      + self.forget_grace_s):
                    # died without a goodbye: ejected by the poll
                    # fallback AND quiet past the grace window
                    self._publish("replica_departed", replica=st.name,
                                  source=st.source, inferred=True)
                    self._forget(st.name)
        self._refresh_gauges()

    def _forget(self, name: str) -> None:
        """The drain-then-forget terminal step (callers hold _mu)."""
        self.router.ring.remove(name)
        self.router.tracker.remove(name)
        self.router.policy.clear_factors(name)
        self.listener.forget(name)
        self._depart_deadline.pop(name, None)
        self._stale.discard(name)
        _FLEET_WEIGHT.labels(replica=name).set(1.0)

    def _refresh_gauges(self) -> None:
        counts: Dict[str, int] = {"static": 0, "announced": 0}
        for st in self.router.tracker.states():
            counts[st.source] = counts.get(st.source, 0) + 1
        for source, n in counts.items():
            _FLEET_REPLICAS.labels(source=source).set(n)

    # -- read surfaces ------------------------------------------------------

    def warmup_retry_after(self) -> Optional[float]:
        """Retry-After for a fleet-wide NoReplicaError during the
        discovery WARM-UP window: no replica has ever reported, so the
        announce interval is the honest bound on when one could — the
        one documented exception to the router's never-invent-a-
        Retry-After contract (a formed fleet that refuses still
        propagates only replica-computed ETAs). None once any replica
        has reported."""
        for st in self.router.tracker.states():
            if st.polled:
                return None
        return max(1.0, self.announce_interval_s)

    def fleet(self) -> Dict:
        """The GET /api/v1/fleet body: per-replica liveness, announce
        age, clock offset, headroom, attainment, epoch, and the
        composed placement weight WITH per-factor provenance."""
        policy = self.router.policy
        fleet: Dict[str, Dict] = {}
        for st in self.router.tracker.states():
            snap = st.snapshot()
            doc = st.doc
            prov = policy.weight_provenance(st.name)
            fleet[st.name] = {
                "live": st.polled and not st.ejected,
                "source": st.source,
                "admitting": st.admitting,
                "draining": st.draining,
                "departing": st.departing,
                "last_announce_age_s": snap["push_age_s"],
                "last_seen_age_s": snap["age_s"],
                "clock_offset_s": snap["clock_offset_s"],
                "load": st.load,
                "config_epoch": st.config_epoch,
                "switch_in_flight": st.switch_in_flight,
                "queue_depth_by_class": doc.get("queue_depth_by_class"),
                "pool": doc.get("pool"),
                "attainment_1m": (doc.get("slo") or {}
                                  ).get("attainment_1m"),
                "anomalies": doc.get("anomalies"),
                "hbm": self.listener.hbm_for(st.name),
                "weight": prov["weight"],
                "weight_provenance": prov["factors"],
            }
        return {"role": "router",
                "announce_port": self.port,
                "announce_interval_s": self.announce_interval_s,
                "stale_after_s": self.stale_after_s,
                "replicas": fleet}

    def render_federated(self, local_families=()) -> str:
        """Replica-labeled federated families for the router's
        /metrics (the PR 11 render_federated pattern, replica= label)."""
        return self.listener.render_federated(local_families)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetDiscovery":
        if self._thread is None:
            t = threading.Thread(target=self._run, daemon=True,
                                 name="cake-router-discovery")
            self._thread = t
            t.start()
        return self

    def _run(self) -> None:
        interval = min(1.0, max(0.05, self.announce_interval_s / 2.0))
        while not self._stop.wait(interval):
            try:
                self.maintain()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("discovery maintenance failed")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.listener.close()
