"""Per-replica state tracking for the front-door router.

Each replica is an independent `--api` engine server. The tracker polls
its cheap health variant (`GET /api/v1/health?lite=1` — api/server.py)
on a short cadence and keeps the last document plus liveness state:

  * a replica whose last successful poll is older than `stale_after_s`
    is EJECTED — no new work routes to it;
  * an ejected replica is re-probed on a jittered exponential backoff
    seeded from its name (the PR 8 HeartbeatSender discipline: a fleet
    of routers restarting must not thundering-herd a recovering
    replica), and one successful probe reinstates it;
  * a hard connection failure observed by the PROXY (connect refused
    mid-request) ejects immediately via `note_failure(hard=True)` —
    the poller's staleness window is an upper bound, not a gate the
    data path must wait out.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence

from cake_tpu.obs import metrics as obs_metrics

log = logging.getLogger(__name__)

# replica-state gauge values (README metrics table): the router's view
# of each backend, refreshed on every poll outcome
STATE_UP = 2.0
STATE_DRAINING = 1.0
STATE_DOWN = 0.0

_REPLICA_STATE = obs_metrics.gauge(
    "cake_router_replica_state",
    "Router's view of each backend replica: 2 up, 1 draining, 0 "
    "ejected/unreachable", labelnames=("replica",))
_POLLS = obs_metrics.counter(
    "cake_router_polls_total",
    "Replica health polls by outcome", labelnames=("outcome",))


def _http_lite_health(name: str, timeout_s: float) -> dict:
    """Default fetch: the lite health doc over HTTP."""
    with urllib.request.urlopen(
            f"http://{name}/api/v1/health?lite=1",
            timeout=timeout_s) as resp:
        return json.loads(resp.read())


class ReplicaState:
    """One backend's last-known state. Reads are lock-free snapshots of
    immutable-once-assigned attributes; the tracker is the one writer."""

    def __init__(self, name: str, source: str = "static"):
        self.name = name
        self.doc: dict = {}
        self.last_ok: Optional[float] = None   # monotonic
        self.failures = 0
        self.ejected = False
        self.next_probe = 0.0                  # monotonic deadline
        # fleet discovery (router/discovery.py): how this replica
        # entered the fleet ("static" = --replicas seed, "announced" =
        # registered by its own announce frame), when its last PUSHED
        # telemetry frame arrived (monotonic; None = never — poll-only),
        # and whether it sent the departure notice (drains-then-forgets:
        # no new admissions, sticky attaches still land, forgotten once
        # its load hits zero)
        self.source = source
        self.last_push: Optional[float] = None
        self.departing = False
        # estimated wall-clock offset of THIS router vs the replica
        # (seconds): min over polls of receive-wall minus the
        # replica's health-reported wall ("now") — skew plus the
        # smallest observed transit, the PR 11 federation rule. The
        # federated timeline corrects replica span timestamps by it.
        # None until a poll carries a clock sample; reset on
        # reinstatement (a restarted replica's clock is fresh news).
        self.clock_offset: Optional[float] = None

    # -- derived views (router policy reads these) -----------------------

    @property
    def polled(self) -> bool:
        return self.last_ok is not None

    @property
    def draining(self) -> bool:
        return bool(self.doc.get("draining"))

    @property
    def breaker_tripped(self) -> bool:
        return bool(self.doc.get("recovery", {})
                    .get("breaker", {}).get("tripped"))

    @property
    def admitting(self) -> bool:
        """New work may route here: polled, not ejected, not draining,
        not departing, breaker not tripped, replica itself reports
        ok."""
        return (self.polled and not self.ejected and not self.draining
                and not self.departing and not self.breaker_tripped
                and self.doc.get("status") == "ok")

    @property
    def switch_in_flight(self) -> bool:
        """The replica reports a live config hot-switch (the compile
        wall): the policy routes AROUND it while another eligible
        replica exists, and restores it automatically when a later
        doc shows the epoch landed."""
        return bool(self.doc.get("switch_in_flight"))

    @property
    def load(self) -> int:
        """Queue depth + active slots — the bounded-load watermark's
        input and the least-loaded tiebreak."""
        return (int(self.doc.get("queue_depth", 0))
                + int(self.doc.get("active_requests", 0)))

    @property
    def config_epoch(self) -> Optional[int]:
        return self.doc.get("config_epoch")

    @property
    def page_size(self) -> Optional[int]:
        return self.doc.get("page_size")

    @property
    def drain_eta_s(self) -> Optional[float]:
        eta = self.doc.get("drain", {}).get("eta_s")
        return float(eta) if eta is not None else None

    def snapshot(self) -> dict:
        """Introspection row for GET /api/v1/router."""
        return {
            "ejected": self.ejected,
            "draining": self.draining,
            "departing": self.departing,
            "source": self.source,
            "admitting": self.admitting,
            "failures": self.failures,
            "load": self.load,
            "config_epoch": self.config_epoch,
            "age_s": (round(time.monotonic() - self.last_ok, 3)
                      if self.last_ok is not None else None),
            "push_age_s": (round(time.monotonic() - self.last_push, 3)
                           if self.last_push is not None else None),
            "clock_offset_s": (round(self.clock_offset, 6)
                               if self.clock_offset is not None
                               else None),
            "replica_reported": self.doc.get("replica"),
        }


class ReplicaTracker:
    """Polls every replica's lite health on `poll_interval_s`.

    `fetch(name) -> dict` is injectable (tests and the bench drive
    in-process replicas without sockets); the default is the HTTP lite
    endpoint. `poll_once()` is the synchronous seam; `start()` runs it
    on a daemon thread.
    """

    # cakelint guards discipline: the poll thread exists only between
    # start() and close()
    OPTIONAL_PLANES = ("_thread",)

    BACKOFF_BASE_S = 0.5
    BACKOFF_MAX_S = 10.0

    def __init__(self, replicas: Sequence[str],
                 poll_interval_s: float = 0.25,
                 stale_after_s: float = 2.0,
                 fetch: Optional[Callable[[str], dict]] = None,
                 timeout_s: float = 1.0,
                 allow_empty: bool = False):
        # allow_empty: fleet discovery (router/discovery.py) grows the
        # fleet from announce frames, so the static seed MAY be empty
        # there; without discovery an empty list is a fleet that can
        # never serve — keep the loud error
        if not replicas and not allow_empty:
            raise ValueError("router needs at least one replica")
        if len(set(replicas)) != len(list(replicas)):
            raise ValueError(f"duplicate replica names in {replicas}")
        if poll_interval_s <= 0 or stale_after_s <= 0:
            raise ValueError("poll_interval_s and stale_after_s must "
                             "be > 0")
        self.poll_interval_s = poll_interval_s
        self.stale_after_s = stale_after_s
        self.timeout_s = timeout_s
        self._fetch = fetch or (
            lambda name: _http_lite_health(name, self.timeout_s))
        self._mu = threading.Lock()
        self._states: Dict[str, ReplicaState] = {
            name: ReplicaState(name) for name in replicas}
        # per-replica jitter rng seeded from the NAME: reproducible,
        # and de-correlated across replicas (the PR 8 discipline)
        self._rng = {name: random.Random(f"cake-router:{name}")
                     for name in replicas}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- views (membership reads take _mu: discovery mutates the dict) ---

    def names(self) -> List[str]:
        with self._mu:
            return sorted(self._states)

    def states(self) -> List[ReplicaState]:
        with self._mu:
            return list(self._states.values())

    def get(self, name: str) -> Optional[ReplicaState]:
        with self._mu:
            return self._states.get(name)

    def admitting(self) -> List[ReplicaState]:
        return [s for s in self.states() if s.admitting]

    def snapshot(self) -> dict:
        with self._mu:
            items = sorted(self._states.items())
        return {name: st.snapshot() for name, st in items}

    # -- dynamic membership (fleet discovery, router/discovery.py) -------

    def add(self, name: str, source: str = "announced") -> bool:
        """Register a replica discovered at runtime. Idempotent: False
        when the name is already tracked (a re-announce refreshes state
        through note_ok, it never double-registers)."""
        with self._mu:
            if name in self._states:
                return False
            self._states[name] = ReplicaState(name, source=source)
            self._rng[name] = random.Random(f"cake-router:{name}")
        log.info("router: replica %s registered (%s)", name, source)
        return True

    def remove(self, name: str) -> bool:
        """Forget a replica (the drain-then-forget terminal step).
        Its state gauge drops to DOWN — the series stays, bounded by
        the names ever fronted."""
        with self._mu:
            st = self._states.pop(name, None)
            self._rng.pop(name, None)
        if st is None:
            return False
        _REPLICA_STATE.labels(replica=name).set(STATE_DOWN)
        log.info("router: replica %s forgotten", name)
        return True

    # -- state transitions (single-writer: poll thread or caller) --------

    def _set_gauge(self, st: ReplicaState) -> None:
        if st.ejected or not st.polled or st.breaker_tripped \
                or st.doc.get("status") != "ok":
            val = STATE_DOWN
        elif st.draining:
            val = STATE_DRAINING
        else:
            val = STATE_UP
        _REPLICA_STATE.labels(replica=st.name).set(val)

    def _backoff_s(self, st: ReplicaState) -> float:
        base = min(self.BACKOFF_MAX_S,
                   self.BACKOFF_BASE_S * (2 ** min(st.failures, 6)))
        rng = self._rng.get(st.name) \
            or random.Random(f"cake-router:{st.name}")
        return base * (0.5 + rng.random())

    def note_ok(self, name: str, doc: dict,
                push: bool = False) -> None:
        """A health document arrived for `name` — from the poll path
        (default) or PUSHED in an announce frame (push=True, fleet
        discovery). A fresh push also stamps last_push, which suppresses
        the redundant poll for one staleness window; when the announce
        stream goes quiet the stamp ages out and polling resumes — the
        fallback-to-poll semantics, no mode switch anywhere."""
        st = self.get(name)
        if st is None:
            return   # forgotten while the doc was in flight
        # clock sample: the health doc's build-time wall clock ("now",
        # api/server.py) against our receive wall. min over polls is
        # the tightest offset bound this channel can observe (the
        # obs/federation.py discipline); a poll without the field
        # (older replica, fake test fetches) just contributes nothing.
        sample = None
        t_wall = doc.get("now") if isinstance(doc, dict) else None
        if isinstance(t_wall, (int, float)):
            sample = time.time() - float(t_wall)
        with self._mu:
            reinstated = st.ejected
            st.doc = doc
            st.last_ok = time.monotonic()
            if push:
                st.last_push = st.last_ok
            st.failures = 0
            st.ejected = False
            st.next_probe = 0.0
            if sample is not None:
                if reinstated or st.clock_offset is None:
                    # a reinstated replica may be a RESTART — its old
                    # min-offset is stale evidence
                    st.clock_offset = sample
                else:
                    st.clock_offset = min(st.clock_offset, sample)
        if reinstated:
            log.info("router: replica %s reinstated", name)
        self._set_gauge(st)
        if not push:
            _POLLS.labels(outcome="ok").inc()

    def note_failure(self, name: str, hard: bool = False) -> None:
        """A poll (or, with hard=True, a data-path connect) failed.
        Ejection is staleness-based for soft failures — one dropped
        poll inside the window must not bounce a loaded replica — and
        immediate for hard ones."""
        st = self.get(name)
        if st is None:
            return   # forgotten while the failure was in flight
        now = time.monotonic()
        with self._mu:
            st.failures += 1
            stale = (st.last_ok is None
                     or now - st.last_ok > self.stale_after_s)
            if (hard or stale) and not st.ejected:
                st.ejected = True
                log.warning("router: ejecting replica %s (%s, %d "
                            "consecutive failures)", name,
                            "hard failure" if hard else "stale",
                            st.failures)
            if st.ejected:
                st.next_probe = now + self._backoff_s(st)
        self._set_gauge(st)
        _POLLS.labels(outcome="fail").inc()

    def poll_once(self, now: Optional[float] = None) -> None:
        """One pass over every replica: fetch lite health, update
        state. Ejected replicas are re-probed only past their jittered
        backoff deadline; replicas whose PUSHED announce frames are
        fresh (within the staleness window) are skipped — the push
        stream already carries liveness, so the poll would be a
        redundant round trip. When frames stop, the stamp ages out and
        this loop resumes polling automatically."""
        now = time.monotonic() if now is None else now
        with self._mu:
            items = list(self._states.items())
        for name, st in items:
            if st.ejected and now < st.next_probe:
                continue
            if (st.last_push is not None
                    and now - st.last_push <= self.stale_after_s):
                continue
            try:
                doc = self._fetch(name)
            except Exception:  # noqa: BLE001 — any failure is a miss
                self.note_failure(name)
            else:
                if not isinstance(doc, dict):
                    self.note_failure(name)
                else:
                    self.note_ok(name, doc)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ReplicaTracker":
        if self._thread is not None:
            return self
        t = threading.Thread(
            target=self._run, daemon=True, name="cake-router-poll")
        self._thread = t
        t.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_interval_s)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
