"""HTTP/SSE proxying between the front door and one replica.

The router forwards the client's body and control headers
(`x-cake-priority`, `x-cake-idempotency-key`, `Last-Event-ID`) to the
chosen replica and relays the response:

  * non-200: status, body, `Retry-After` and `x-cake-replica` headers
    relay VERBATIM — a replica's computed backpressure is the honest
    one, the router never rewrites it;
  * 200 JSON: body relays as-is;
  * 200 SSE: the event stream passes through line-by-line with `id:`
    fields preserved (absolute token positions — `Last-Event-ID`
    reconnects keep working through the router, across replicas);
  * a replica dying MID-STREAM surfaces as a terminal SSE error event
    (typed `ReplicaDownError`, retryable) — never a silent close the
    client cannot tell from success.

Outcomes are returned as ProxyOutcome values so the server's failover
loop can decide: retry elsewhere (nothing reached the client yet) or
stop (bytes are already on the wire / the response was relayed).
"""

from __future__ import annotations

import http.client
import json
import logging
import time
from typing import Callable, Optional

from cake_tpu.obs import metrics as obs_metrics

log = logging.getLogger(__name__)

# headers the router forwards to the replica; everything else is
# hop-local (Content-Length is recomputed, Host rewritten by httplib).
# Trace context (x-cake-trace / x-cake-hop) is NOT in this list: the
# router owns it — the server passes the minted/propagated values via
# extra_headers so a client cannot smuggle a conflicting hop count past
# the front door.
FORWARD_HEADERS = ("x-cake-priority", "x-cake-idempotency-key",
                   "Last-Event-ID")
# response headers relayed verbatim on a non-200 (the honest
# backpressure surface: the replica computed them, the router must
# not; x-cake-trace rides along so a refused request still hands the
# client its trace id)
RELAY_HEADERS = ("Retry-After", "x-cake-replica", "x-cake-trace")

_TTFT = obs_metrics.histogram(
    "cake_router_ttft_seconds",
    "Router-observed time from forwarding a streaming request to its "
    "first SSE data event")


class ProxyOutcome:
    """What happened to one forward attempt.

    kind:
      * "ok"        — 200 relayed to completion (stream or JSON)
      * "relayed"   — non-200 relayed verbatim (status carries it)
      * "retryable" — nothing reached the client; the server may fail
                      over to another replica (connect failure, or a
                      refusal `should_failover` classified as roamable:
                      draining 429, switch 409, retryable 503)
      * "midstream" — the stream broke after bytes reached the client;
                      a terminal SSE error event was written
    """

    __slots__ = ("kind", "status", "retry_after_s", "error", "draining",
                 "hard")

    def __init__(self, kind: str, status: int = 0,
                 retry_after_s: Optional[float] = None,
                 error: str = "", draining: bool = False,
                 hard: bool = False):
        self.kind = kind
        self.status = status
        self.retry_after_s = retry_after_s
        self.error = error
        self.draining = draining
        # hard: CONNECT-level failure — nothing listens there, strong
        # evidence the replica is gone (the server hard-ejects it).
        # Post-connect breaks (header timeout, body/stream cut) stay
        # soft: a busy replica queueing admissions is not a corpse.
        self.hard = hard


def classify_refusal(status: int, body: bytes) -> str:
    """Split replica refusals into roamable vs terminal.

    Roamable (another replica may well admit this request): a DRAIN
    429 (this replica is leaving the fleet), a 409 (config switch in
    flight) and a retryable 503 (transient engine reset). Terminal
    (relay verbatim): shed/queue-full 429 — the replica measured its
    own saturation and computed an honest Retry-After; 4xx client
    errors; non-retryable 500s (poison)."""
    if status == 409:
        return "switch"
    try:
        doc = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        doc = {}
    if status == 429 and "draining" in str(doc.get("error", "")):
        return "draining"
    if status == 503 and doc.get("retryable") is True:
        return "reset"
    return ""


class ReplicaProxy:
    """One forward attempt per call; connections are per-request (the
    replica's keep-alive is its own business — the router's fan-out is
    bounded by client concurrency, not a pool)."""

    def __init__(self, connect_timeout_s: float = 2.0,
                 stream_idle_timeout_s: float = 600.0,
                 header_timeout_s: float = 300.0):
        self.connect_timeout_s = connect_timeout_s
        self.stream_idle_timeout_s = stream_idle_timeout_s
        # bound on the wait for the replica's response STATUS LINE: a
        # replica whose accept loop died with its listen socket still
        # open (mid-drain shutdown, wedged process) would otherwise
        # blackhole requests for the full idle timeout instead of
        # roaming. Streaming responses send headers at ADMISSION, so
        # this costs them nothing; non-stream responses arrive only
        # when generation completes — keep the bound above the longest
        # expected non-stream generation (or use streaming behind a
        # router).
        self.header_timeout_s = header_timeout_s

    def forward_chat(self, replica: str, path: str, body_bytes: bytes,
                     headers: dict, stream: bool,
                     send_status: Callable[[int, dict, bytes], None],
                     send_line: Callable[[bytes], None],
                     send_terminal_error: Callable[[str], None],
                     on_admitted: Optional[Callable[..., None]] = None,
                     extra_headers: Optional[dict] = None,
                     on_hop: Optional[Callable[..., None]] = None,
                     ) -> ProxyOutcome:
        """Forward one chat request.

        send_status(code, relay_headers, body) — relay a complete
        non-stream response. send_line(raw) — relay one SSE line
        (already includes the newline). send_terminal_error(msg) —
        write the typed terminal SSE error event (only called after
        send_line delivered bytes). on_admitted(rid=...) fires as soon
        as the replica answers 200 — i.e. the request holds a slot
        THERE — so idempotency-sticky state exists before the stream
        finishes (a mid-stream reconnect must find its home); rid is
        the replica's echoed x-cake-rid (None when absent).
        extra_headers are router-owned forwards (the trace context)
        merged OVER the client's. on_hop(name, **fields) records hop
        spans live ("connect", "first_byte") for the router's tracer —
        live, because a streaming relay returns only when the stream
        ends, long after both happened."""
        fwd = {"Content-Type": "application/json"}
        for h in FORWARD_HEADERS:
            v = headers.get(h)
            if v is not None:
                fwd[h] = v
        if extra_headers:
            fwd.update(extra_headers)
        # the SHORT timeout covers only the TCP connect (a dead replica
        # must fail over in milliseconds); the response itself may
        # legitimately take a long generation (non-stream requests
        # answer only when done), so the socket relaxes to the idle
        # timeout once connected
        conn = http.client.HTTPConnection(
            replica, timeout=self.connect_timeout_s)
        t0 = time.perf_counter()
        try:
            conn.connect()
        except OSError as e:
            conn.close()
            return ProxyOutcome("retryable", hard=True,
                                error=f"connect failed: {e}")
        if on_hop is not None:
            on_hop("connect")
        try:
            conn.sock.settimeout(self.header_timeout_s)
            conn.request("POST", path, body=body_bytes, headers=fwd)
            resp = conn.getresponse()
            conn.sock.settimeout(self.stream_idle_timeout_s)
        except OSError as e:
            # post-connect: the replica is there but slow/broken —
            # roam, but do NOT treat it as a corpse
            conn.close()
            return ProxyOutcome("retryable",
                                error=f"request/header failed: {e}")

        try:
            if resp.status != 200:
                try:
                    data = resp.read()
                except (OSError, http.client.HTTPException) as e:
                    # body cut mid-read; nothing reached the client
                    return ProxyOutcome(
                        "retryable", error=f"refusal body cut: {e}")
                roam = classify_refusal(resp.status, data)
                relay = {h: resp.getheader(h) for h in RELAY_HEADERS
                         if resp.getheader(h) is not None}
                ra = resp.getheader("Retry-After")
                if roam:
                    return ProxyOutcome(
                        "retryable", status=resp.status,
                        retry_after_s=float(ra) if ra else None,
                        error=roam, draining=(roam == "draining"))
                send_status(resp.status, relay, data)
                return ProxyOutcome(
                    "relayed", status=resp.status,
                    retry_after_s=float(ra) if ra else None)

            if on_admitted is not None:
                rid_h = resp.getheader("x-cake-rid")
                try:
                    rid_v = int(rid_h) if rid_h is not None else None
                except ValueError:
                    rid_v = None
                on_admitted(rid=rid_v)
            ctype = resp.getheader("Content-Type", "")
            if not stream or "text/event-stream" not in ctype:
                try:
                    data = resp.read()
                except (OSError, http.client.HTTPException) as e:
                    # the replica died mid-body: nothing reached the
                    # client yet, so this request can still roam (the
                    # keyed case re-homes; a completed-but-cut
                    # transcript re-serves via the idempotent attach)
                    return ProxyOutcome(
                        "retryable", error=f"response body cut: {e}")
                if on_hop is not None:
                    # non-stream: the whole body IS the first byte the
                    # client sees (generation answers only when done)
                    on_hop("first_byte",
                           ttft_s=round(time.perf_counter() - t0, 6))
                relay = {h: resp.getheader(h) for h in RELAY_HEADERS
                         if resp.getheader(h) is not None}
                send_status(200, relay, data)
                return ProxyOutcome("ok", status=200)

            # SSE pass-through. The replica sent its headers only after
            # admission (api/server.py on_start), so a 200 here means
            # the request holds a slot — from now on a break is
            # mid-stream, not a failover.
            first = True
            sent_any = False
            saw_terminal = False
            while True:
                try:
                    line = resp.readline()
                except (OSError, http.client.HTTPException) as e:
                    if not sent_any:
                        # admitted but nothing reached the client yet:
                        # safe to roam to another replica
                        return ProxyOutcome(
                            "retryable",
                            error=f"stream broke before first event: "
                                  f"{e}")
                    log.warning("replica %s died mid-stream: %s",
                                replica, e)
                    send_terminal_error(
                        f"replica {replica} went away mid-stream "
                        f"({type(e).__name__}); reconnect with your "
                        "idempotency key and Last-Event-ID to resume")
                    return ProxyOutcome("midstream", error=str(e))
                if not line:
                    if not sent_any:
                        # admitted but died before the first event:
                        # nothing reached the client — roam
                        return ProxyOutcome(
                            "retryable",
                            error="stream closed before first event")
                    if not saw_terminal:
                        # EOF without [DONE] or an error event: the
                        # replica's socket closed under the stream —
                        # surface it, never a silent close
                        send_terminal_error(
                            f"replica {replica} closed the stream "
                            "without finishing; reconnect with your "
                            "idempotency key and Last-Event-ID to "
                            "resume")
                        return ProxyOutcome(
                            "midstream", error="eof without terminal")
                    return ProxyOutcome("ok", status=200)
                if first and line.startswith((b"data:", b"id:")):
                    ttft = time.perf_counter() - t0
                    _TTFT.observe(ttft)
                    if on_hop is not None:
                        on_hop("first_byte", ttft_s=round(ttft, 6))
                    first = False
                # terminal markers: the exact [DONE] sentinel line or
                # the typed error event ({"error": {...}} — a delta
                # containing the literal text would JSON-escape its
                # quotes)
                if line.strip() == b"data: [DONE]" or (
                        line.startswith(b'data: {"error":')):
                    saw_terminal = True
                try:
                    send_line(line)
                    sent_any = True
                except OSError:
                    # the CLIENT went away; nothing more to relay (the
                    # replica stream is abandoned with this connection
                    # close — a keyed request keeps decoding replica-
                    # side for the reconnect)
                    return ProxyOutcome("ok", status=200,
                                        error="client disconnected")
        finally:
            conn.close()
