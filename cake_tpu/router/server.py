"""The router HTTP front door.

`cake-tpu --router --replicas host:port,host:port,...` runs THIS
process role — no model, no devices: a ThreadingHTTPServer that routes
each chat request to one of N engine replicas (policy.py), proxies the
response through (proxy.py), and serves its own introspection:

  * POST /api/v1/chat/completions (+ /v1 alias) — routed + proxied
  * GET  /api/v1/router — replica states, policy mode, sticky keys
  * GET  /api/v1/health — the ROUTER's own health (cheap; replicas'
    health is what the tracker polls)
  * GET  /api/v1/requests/{rid}/timeline — the FEDERATED per-request
    explain: router hop spans + the owning replica(s)' merged
    timelines (both after a failover), clock-offset-corrected into
    one wall-clock chronology (ISSUE 15)
  * GET  /api/v1/events — the router-tier typed event ring
    (affinity_miss / spill_to_secondary / failover_resume /
    shed_by_router, keyed by trace id)
  * GET  /api/v1/anomalies — the --sentinel regression sentinel's
    active + recent anomalies (obs/sentinel.py)
  * GET  /metrics — the cake_router_* + cake_anomaly_* families

Every routed request carries trace context: the router propagates the
client's `x-cake-trace` (or continues a keyed request's recorded
trace, or mints one), forwards it with `x-cake-hop` to the replica —
which threads it through its tracer/event bus and echoes it on SSE
and error responses — and hands it back to the client on the SSE
response headers together with `x-cake-replica` / `x-cake-rid`.

Failover loop: a connect failure or a roamable refusal (draining 429,
switch 409, retryable 503) moves the request to the next pick until
every replica was tried; a shed/queue-full 429 relays VERBATIM with
the replica's computed Retry-After and x-cake-replica attribution. A
replica dying mid-stream surfaces as a terminal SSE error event; the
client's keyed reconnect (Last-Event-ID) re-routes — sticky to the
home replica while it lives, re-admitted elsewhere once it is ejected
(the engine-side fresh-admission Last-Event-ID suppression keeps the
resumed stream exact-suffix).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs.events import EventBus
from cake_tpu.router.affinity import (
    HashRing, prefix_fingerprint, text_fingerprint,
)
# _FAILOVERS is single-sourced in policy.py (which increments it for
# sticky home_ejected re-homes); a second declaration here would have
# to keep its help string byte-identical forever
from cake_tpu.router.policy import (
    _FAILOVERS, NoReplicaError, RoutingPolicy,
)
from cake_tpu.router.proxy import ReplicaProxy
from cake_tpu.router.replicas import ReplicaTracker
from cake_tpu.router.tracing import HopTracer

log = logging.getLogger(__name__)

# rid-bearing paths count/route under their template, same rule as
# api/server.py — a per-rid route would be unbounded
_TIMELINE_RE = re.compile(r"^/api/v1/requests/(\d+)/timeline$")

_REQUESTS = obs_metrics.counter(
    "cake_router_requests_total",
    "Chat requests proxied, by backend replica and priority class",
    labelnames=("replica", "class"))
_SHEDS = obs_metrics.counter(
    "cake_router_sheds_total",
    "Requests the router could not place (no_replica) or relayed a "
    "replica refusal for (relay)", labelnames=("reason",))

DEFAULT_PAGE_SIZE = 128


class RouterServer:
    """Routing + proxy state shared by the handler threads."""

    # cakelint guards discipline: the tokenizer (page-aligned affinity
    # keys), the decision JSONL log, the hop tracer, the typed event
    # ring, the regression sentinel and the fleet-discovery plane are
    # all optional planes — every dereference is `is not None`-guarded,
    # machine-checked from day one (the PR 13/14 precedent)
    OPTIONAL_PLANES = ("tokenizer", "_log", "hops", "events",
                       "sentinel", "actions", "discovery")

    def __init__(self, replicas, tokenizer=None,
                 poll_interval_s: float = 0.25,
                 stale_after_s: float = 2.0,
                 load_watermark: int = 8,
                 policy_mode: str = "affinity",
                 fetch=None, decision_log: Optional[str] = None,
                 vnodes: int = 64,
                 trace_ring: int = 256,
                 trace_events: Optional[str] = None,
                 event_ring: int = 1024,
                 event_log: Optional[str] = None,
                 sentinel: bool = False,
                 sentinel_interval_s: float = 2.0,
                 anomaly_weighting: bool = False,
                 fetch_timeline=None,
                 timeline_timeout_s: float = 5.0,
                 announce: Optional[str] = None,
                 announce_interval_s: float = 2.0,
                 announce_token: Optional[str] = None,
                 forget_grace_s: float = 30.0):
        self.tokenizer = tokenizer
        # with fleet discovery armed the static --replicas seed MAY be
        # empty: the fleet forms from announce frames
        self.tracker = ReplicaTracker(
            replicas, poll_interval_s=poll_interval_s,
            stale_after_s=stale_after_s, fetch=fetch,
            allow_empty=announce is not None)
        self.ring = HashRing(self.tracker.names(), vnodes=vnodes)
        self.policy = RoutingPolicy(
            self.tracker, ring=self.ring,
            load_watermark=load_watermark, mode=policy_mode)
        self.proxy = ReplicaProxy()
        self._log = None
        if decision_log:
            from cake_tpu.obs.jsonl import JsonlAppender
            self._log = JsonlAppender(decision_log)
        # distributed tracing (router/tracing.py): per-request hop
        # records keyed by the minted/propagated x-cake-trace id, the
        # front-door half of GET /api/v1/requests/{rid}/timeline.
        # trace_ring 0 disables the plane (every site is then one
        # attribute test — the --event-ring 0 discipline).
        self.hops = (HopTracer(trace_ring, events_path=trace_events)
                     if trace_ring > 0 else None)
        # router-tier typed event ring (obs/events.py vocabulary:
        # affinity_miss / spill_to_secondary / failover_resume /
        # shed_by_router, events carry trace= not rid=), served at
        # GET /api/v1/events with an optional --event-log JSONL sink
        self.events = (EventBus(capacity=event_ring,
                                log_path=event_log)
                       if event_ring > 0 else None)
        # online regression sentinel (--sentinel, obs/sentinel.py):
        # per-replica TTFT skew, affinity collapse, router shed storms
        self.sentinel = None
        if sentinel:
            from cake_tpu.obs.sentinel import attach_router_sentinel
            self.sentinel = attach_router_sentinel(
                self, interval_s=sentinel_interval_s)
        # closed-loop anomaly weighting (--router-anomaly-weighting,
        # obs/actions.py): TTFT-skew / shed-storm / affinity-collapse
        # anomalies de-weight the offending replica's placement (and
        # re-weight on recovery), every action audited on the plane.
        # None without the flag — report-only stays byte-identical.
        self.actions = None
        if anomaly_weighting:
            if self.sentinel is None:
                raise ValueError(
                    "--router-anomaly-weighting requires --sentinel "
                    "with the hop tracer enabled (trace_ring > 0)")
            from cake_tpu.obs.actions import (
                ActionPlane, RouterAnomalyActuator,
            )
            self.actions = ActionPlane(events=self.events)
            RouterAnomalyActuator(self, self.actions).attach(
                self.sentinel)
        # fleet discovery (--router-announce, router/discovery.py):
        # replicas self-register over the token-gated announce channel,
        # pushed frames supersede polling while fresh, departures
        # drain-then-forget, and pushed headroom/attainment become
        # placement weight factors. None without the flag — the static
        # polled fleet stays byte-identical.
        self.discovery = None
        if announce is not None:
            from cake_tpu.router.discovery import FleetDiscovery
            self.discovery = FleetDiscovery(
                self, address=announce, token=announce_token,
                announce_interval_s=announce_interval_s,
                forget_grace_s=forget_grace_s)
        self._timeline_timeout_s = timeline_timeout_s
        # injectable replica-timeline fetch (tests / bench drive
        # in-process replicas); default is the HTTP GET
        self._fetch_timeline = fetch_timeline or self._http_timeline
        if tokenizer is None:
            log.warning(
                "router: no tokenizer — affinity keys fall back to "
                "system-prompt TEXT fingerprints (stable, but not "
                "page-aligned; pass the model's tokenizer for the "
                "register_prefix rounding rule)")

    # -- affinity keys ---------------------------------------------------

    def _page_size(self) -> int:
        """The fleet's kv page size, read from any polled replica's
        lite health (replicas of one deployment share a config);
        default when nothing has reported one yet."""
        for st in self.tracker.states():
            if st.page_size:
                return int(st.page_size)
        return DEFAULT_PAGE_SIZE

    def affinity_key(self, body: dict) -> Optional[str]:
        """The request's shareable-head fingerprint: the rendered
        system-message head (exactly what the engine's --auto-prefix
        registers), page-aligned through the tokenizer when one is
        available."""
        msgs = body.get("messages") or []
        if not msgs or not isinstance(msgs[0], dict):
            return None
        if str(msgs[0].get("role", "")).lower() != "system":
            return None
        from cake_tpu.models.chat import BEGIN_OF_TEXT, History, Message
        try:
            head = BEGIN_OF_TEXT + History.encode_message(
                Message.from_json(msgs[0]))
        except (ValueError, AttributeError):
            return None
        if self.tokenizer is None:
            return text_fingerprint(head)
        from cake_tpu.models.llama.generator import encode_text
        ids = encode_text(self.tokenizer, head)
        return prefix_fingerprint(ids, self._page_size())

    # -- introspection ---------------------------------------------------

    def state(self) -> dict:
        return {
            "role": "router",
            "policy": self.policy.mode,
            "load_watermark": self.policy.load_watermark,
            "replicas": self.tracker.snapshot(),
            "page_size": self._page_size(),
            "affinity": ("paged" if self.tokenizer is not None
                         else "text"),
            "tracing": self.hops is not None,
            "sentinel": self.sentinel is not None,
            "anomaly_weighting": self.actions is not None,
            "discovery": self.discovery is not None,
            "announce_port": (self.discovery.port
                              if self.discovery is not None else None),
            "weights": self.policy.weights(),
        }

    def health(self) -> dict:
        up = [s.name for s in self.tracker.admitting()]
        return {"status": "ok" if up else "degraded",
                "role": "router",
                "replicas_admitting": up,
                "replicas_total": len(self.tracker.names())}

    def note_decision(self, rec: dict) -> None:
        if self._log is not None:
            self._log.append(rec)

    def metrics(self) -> str:
        text = obs_metrics.REGISTRY.render()
        if self.discovery is not None:
            # replica-labeled federated families from announce frames
            # appended after the local render (the PR 11 pattern):
            # families the router also owns reuse its HELP/TYPE block,
            # replica-only families bring their own
            try:
                text += self.discovery.render_federated(
                    {f.name for f in obs_metrics.REGISTRY.families()})
            except Exception:  # noqa: BLE001 — a scrape must not fail
                log.debug("federated render failed", exc_info=True)
        return text

    def fleet(self) -> dict:
        """GET /api/v1/fleet: per-replica liveness, announce age,
        clock offset, headroom, attainment, epoch and the composed
        placement weight with provenance. Without discovery the router
        still answers with the polled view (weights included) so the
        endpoint is one stop regardless of how the fleet formed."""
        if self.discovery is not None:
            return self.discovery.fleet()
        fleet = {}
        for st in self.tracker.states():
            snap = st.snapshot()
            prov = self.policy.weight_provenance(st.name)
            snap["live"] = st.polled and not st.ejected
            snap["weight"] = prov["weight"]
            snap["weight_provenance"] = prov["factors"]
            fleet[st.name] = snap
        return {"role": "router", "replicas": fleet,
                "note": "fleet discovery disabled (start the router "
                        "with --router-announce)"}

    # -- federated per-request explain ------------------------------------

    def _http_timeline(self, replica: str, rid: int) -> dict:
        """Default replica-timeline fetch: the replica's own merged
        explain document over HTTP."""
        with urllib.request.urlopen(
                f"http://{replica}/api/v1/requests/{rid}/timeline",
                timeout=self._timeline_timeout_s) as resp:
            return json.loads(resp.read())

    def request_timeline(self, rid: int) -> Optional[dict]:
        """GET /api/v1/requests/{rid}/timeline, ROUTER tier: resolve
        the rid to its hop record (the replica echoed x-cake-rid at
        admission), fetch the owning replica's merged timeline — BOTH
        replicas' after a failover resume — correct each by its polled
        clock offset, and merge with the router hop spans and router
        event-ring causes into one wall-clock-ordered view
        (obs/timeline.merge_router_timeline). None when the rid is
        unknown here (never admitted through this router, or fell out
        of the hop ring) — the handler's 404."""
        if self.hops is None:
            return None
        rec = self.hops.find_by_rid(rid)
        if rec is None:
            return None
        tid = rec["trace"]
        router_events = []
        if self.events is not None:
            router_events = [e for e in self.events.dump()
                             if e.get("trace") == tid]
        # one fetch per (replica, rid) admission, first-admission
        # order — the failover story reads home-then-survivor
        seen = set()
        replica_docs = []
        for att in rec.get("attempts", ()):
            arid = att.get("rid")
            name = att.get("replica")
            if arid is None or (name, arid) in seen:
                continue
            seen.add((name, arid))
            st = self.tracker.get(name)
            offset = (st.clock_offset if st is not None
                      and st.clock_offset is not None else 0.0)
            try:
                doc = self._fetch_timeline(name, arid)
                if not isinstance(doc, dict):
                    doc = None
            except Exception:  # noqa: BLE001 — a killed home cannot
                # answer; its attempt still reads from the router hops
                log.debug("timeline fetch from %s failed", name,
                          exc_info=True)
                doc = None
            replica_docs.append((name, offset, arid, doc))
        from cake_tpu.obs.timeline import merge_router_timeline
        return merge_router_timeline(rec, router_events, replica_docs)

    def events_page(self, type: Optional[str] = None,
                    since: Optional[int] = None,
                    limit: Optional[int] = None,
                    trace: Optional[str] = None) -> dict:
        """GET /api/v1/events (router tier): the router event ring,
        cursor-paged exactly like the replica endpoint; ?trace=
        additionally selects one trace's events (the router's events
        carry trace ids, not rids)."""
        if self.events is None:
            return {"events": [], "cursor": 0,
                    "note": "router event ring disabled "
                            "(--event-ring 0)"}
        if trace is None:
            evs, cursor = self.events.snapshot(type=type, since=since,
                                               limit=limit)
            return {"events": evs, "cursor": cursor}
        # trace filter BEFORE limiting (limit-then-filter would
        # silently drop matching events while the cursor advanced
        # past them); the truncated-page cursor rule mirrors
        # EventBus.snapshot — the last RETURNED seq, so the next
        # ?since= resumes exactly after it
        evs, cursor = self.events.snapshot(type=type, since=since)
        evs = [e for e in evs if e.get("trace") == trace]
        truncated = limit is not None and len(evs) > max(0, int(limit))
        if limit is not None:
            evs = evs[:max(0, int(limit))]
        if truncated:
            cursor = evs[-1]["seq"] if evs else \
                (since if since is not None else 0)
        return {"events": evs, "cursor": cursor}

    def anomalies(self) -> dict:
        """GET /api/v1/anomalies (router tier), with the closed-loop
        action history and live placement weights when
        --router-anomaly-weighting is armed."""
        if self.sentinel is None:
            return {"active": [], "anomalies": [],
                    "note": "sentinel disabled (start the router with "
                            "--sentinel)"}
        out = self.sentinel.state()
        if self.actions is not None:
            out["actions"] = self.actions.history()
            out["action_rate_per_min"] = self.actions.max_per_min
            out["weights"] = self.policy.weights()
        return out

    def close(self) -> None:
        if self.discovery is not None:
            # stop ingesting announce frames BEFORE the tracker goes
            # down: a frame landing mid-teardown must not re-register
            self.discovery.close()
        if self.sentinel is not None:
            self.sentinel.close()
        self.tracker.close()
        if self.hops is not None:
            self.hops.close()
        if self.events is not None:
            self.events.close()
        if self._log is not None:
            self._log.close()


def make_router_handler(router: RouterServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log.debug("router http: " + fmt, *args)

        def _json(self, code: int, obj: dict,
                  headers: Optional[dict] = None):
            data = json.dumps(obj).encode()
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _query(self) -> dict:
            if "?" not in self.path:
                return {}
            from urllib.parse import parse_qs
            return {k: v[0] for k, v in
                    parse_qs(self.path.split("?", 1)[1]).items() if v}

        def do_GET(self):
            route = self.path.split("?", 1)[0]
            if route == "/api/v1/router":
                return self._json(200, router.state())
            if route == "/api/v1/health":
                return self._json(200, router.health())
            m = _TIMELINE_RE.match(route)
            if m:
                tl = router.request_timeline(int(m.group(1)))
                if tl is None:
                    return self._json(404, {
                        "error": f"unknown rid {m.group(1)} at this "
                                 "router (not admitted through it, "
                                 "hop tracing disabled, or fell out "
                                 "of the hop ring)"})
                return self._json(200, tl)
            if route == "/api/v1/events":
                q = self._query()
                try:
                    t = q.get("type")
                    if t is not None:
                        from cake_tpu.obs.events import EVENT_TYPES
                        if t not in EVENT_TYPES:
                            raise ValueError(
                                f"unknown event type {t!r} (choose "
                                f"one of {', '.join(EVENT_TYPES)})")
                    since = q.get("since")
                    limit = q.get("limit")
                    return self._json(200, router.events_page(
                        type=t,
                        since=int(since) if since is not None else None,
                        limit=int(limit) if limit is not None else None,
                        trace=q.get("trace")))
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
            if route == "/api/v1/anomalies":
                return self._json(200, router.anomalies())
            if route == "/api/v1/fleet":
                return self._json(200, router.fleet())
            if route in ("/metrics", "/api/v1/metrics"):
                data = router.metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._json(404, {"error": "not found (router process: "
                                      "chat + router introspection "
                                      "only)"})

        def do_POST(self):
            route = self.path.split("?", 1)[0]
            if route not in ("/api/v1/chat/completions",
                             "/v1/chat/completions"):
                return self._json(404, {
                    "error": "not found (the router fronts chat "
                             "completions; administrative endpoints "
                             "live on the replicas)"})
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b"{}"
            try:
                body = json.loads(raw)
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as e:
                return self._json(400, {"error": f"invalid JSON body: "
                                                 f"{e}"})
            try:
                self._route_chat(route, raw, body)
            except OSError as e:
                # the CLIENT went away while we wrote its response
                # (broken pipe out of a relay/shed write): nothing to
                # tell anyone — but the hop record must still reach a
                # terminal state, or it would sit in the tracer's
                # active set forever (finish() is a no-op when the
                # route already finished it)
                log.debug("client disconnected mid-response: %s", e)
                tid = getattr(self, "_trace_id", None)
                if tid is not None and router.hops is not None:
                    router.hops.finish(tid, "error",
                                       error="client disconnected")

        # -- routed chat -------------------------------------------------

        def _route_chat(self, route: str, raw: bytes,
                        body: dict) -> None:
            cls = body.get("priority") \
                or self.headers.get("x-cake-priority") or "standard"
            if not isinstance(cls, str):
                cls = "standard"
            idem = self.headers.get("x-cake-idempotency-key")
            stream = bool(body.get("stream"))
            try:
                key = router.affinity_key(body)
            except Exception:  # noqa: BLE001 — affinity is best-effort
                log.debug("affinity key failed", exc_info=True)
                key = None

            # trace context: propagate the client's x-cake-trace, else
            # CONTINUE a keyed request's original trace (the sticky map
            # remembers it — a failover resume is one story), else mint.
            # x-cake-hop counts front-door tiers: the router forwards
            # its own count + 1 so a multi-router chain stays legible.
            tid = self.headers.get("x-cake-trace") \
                or router.policy.sticky_trace(idem)
            if not tid:
                tid = uuid.uuid4().hex
            try:
                hop_n = int(self.headers.get("x-cake-hop", 0)) + 1
            except ValueError:
                hop_n = 1
            self._trace_id = tid
            self._sse_meta = None   # (replica, rid) once admitted
            resuming = self.headers.get("Last-Event-ID") is not None
            if router.hops is not None:
                router.hops.begin(tid, cls=cls, stream=stream,
                                  hop=hop_n)

            self._stream_started = False
            tried: set = set()
            last_refusal_ra = None
            while True:
                try:
                    decision = router.policy.route(
                        key=key, idem_key=idem, exclude=tried)
                except NoReplicaError as e:
                    _SHEDS.labels(reason="no_replica").inc()
                    router.note_decision({
                        "event": "shed", "class": cls, "trace": tid,
                        "tried": sorted(tried)})
                    if router.hops is not None:
                        router.hops.finish(tid, "shed",
                                           tried=sorted(tried))
                    if router.events is not None:
                        router.events.publish(
                            "shed_by_router", trace=tid, priority=cls,
                            tried=sorted(tried))
                    hdrs = {"x-cake-trace": tid}
                    # a REPLICA-computed Retry-After only: the drain
                    # ETA from a lite-health doc, or the one carried
                    # by the last roamable refusal this very request
                    # saw — the router never invents its own
                    ra = (e.retry_after_s if e.retry_after_s is not None
                          else last_refusal_ra)
                    if ra is None and router.discovery is not None:
                        # the documented exception: during the
                        # discovery WARM-UP window (no replica has ever
                        # reported) the announce interval is an honest
                        # bound on when one could — without it an empty
                        # forming fleet reads as unretryable
                        ra = router.discovery.warmup_retry_after()
                    if ra is not None:
                        hdrs["Retry-After"] = str(
                            max(1, int(-(-ra // 1))))
                    return self._json(503, {
                        "error": "no replica available",
                        "trace": tid,
                        "tried": sorted(tried),
                        "retryable": True}, headers=hdrs)

                name = decision.replica
                if router.hops is not None:
                    router.hops.attempt(tid, name, decision.outcome)
                    router.hops.span(tid, "pick", replica=name,
                                     outcome=decision.outcome,
                                     sticky=decision.sticky,
                                     spill_reason=decision.spill_reason)
                if router.events is not None and key is not None \
                        and decision.outcome == "spill":
                    # router-tier causes: the request did not land on
                    # its affinity home — and when the home was merely
                    # SATURATED, this was the bounded-load spill to a
                    # secondary ring node specifically
                    router.events.publish(
                        "affinity_miss", trace=tid, replica=name,
                        reason=decision.spill_reason)
                    if decision.spill_reason == "saturated":
                        router.events.publish(
                            "spill_to_secondary", trace=tid,
                            replica=name)
                if resuming and (tried or not decision.sticky):
                    # a keyed client resuming a broken stream somewhere
                    # OTHER than its live sticky home: the drain/kill
                    # failover-resume path (fresh admission +
                    # Last-Event-ID suppression on the new replica)
                    resuming = False   # one cause per request
                    if router.hops is not None:
                        router.hops.span(tid, "failover_resume",
                                         replica=name)
                    if router.events is not None:
                        router.events.publish(
                            "failover_resume", trace=tid, replica=name)

                def admitted(rid=None, name=name):
                    # as soon as the replica 200s: the request holds a
                    # slot there, so keyed reconnects must find this
                    # home even while the stream is still running; the
                    # echoed x-cake-rid joins this trace to the
                    # replica-local record for the federated timeline
                    _REQUESTS.labels(name, cls).inc()
                    router.policy.note_admitted(idem, name, trace=tid)
                    self._sse_meta = (name, rid)
                    if router.hops is not None:
                        router.hops.admitted(tid, name, rid)

                def hop(span_name, name=name, **fields):
                    if router.hops is not None:
                        router.hops.span(tid, span_name, replica=name,
                                         **fields)

                outcome = router.proxy.forward_chat(
                    name, route, raw, self.headers, stream,
                    send_status=self._relay_status,
                    send_line=self._relay_line,
                    send_terminal_error=(
                        lambda msg, name=name:
                        self._terminal_error(msg, replica=name)),
                    on_admitted=admitted,
                    on_hop=hop,
                    extra_headers={"x-cake-trace": tid,
                                   "x-cake-hop": str(hop_n)})
                router.note_decision({
                    "event": "route", "replica": name,
                    "outcome": decision.outcome, "class": cls,
                    "trace": tid,
                    "proxy": outcome.kind, "status": outcome.status})

                if outcome.kind == "retryable":
                    tried.add(name)
                    if router.hops is not None:
                        router.hops.span(tid, "roam", replica=name,
                                         error=outcome.error)
                    if outcome.retry_after_s is not None:
                        last_refusal_ra = outcome.retry_after_s
                    if outcome.hard:
                        # connect-level failure: strong evidence —
                        # eject now, probe later (the poller would
                        # take a staleness window to notice)
                        router.tracker.note_failure(name, hard=True)
                        _FAILOVERS.labels(reason="connect").inc()
                    else:
                        # post-connect: either a roamable REFUSAL
                        # (draining/switch/reset — a protocol answer
                        # from a live replica, no failure evidence) or
                        # a genuine break (header timeout, cut body —
                        # soft evidence: a busy replica is not a
                        # corpse). Labels stay bounded either way.
                        reason = (outcome.error if outcome.error in
                                  ("draining", "switch", "reset")
                                  else "replica_error")
                        if reason == "replica_error":
                            router.tracker.note_failure(name)
                        _FAILOVERS.labels(reason=reason).inc()
                    continue
                if outcome.kind == "midstream":
                    _FAILOVERS.labels(reason="midstream").inc()
                    router.tracker.note_failure(name)
                    if router.hops is not None:
                        router.hops.finish(tid, "midstream",
                                           replica=name,
                                           error=outcome.error)
                    return
                if outcome.kind == "relayed":
                    _SHEDS.labels(reason="relay").inc()
                    if router.hops is not None:
                        router.hops.finish(tid, "relayed",
                                           replica=name,
                                           status=outcome.status)
                    return
                # "ok": relay complete (admission was counted by the
                # on_admitted callback when the 200 arrived)
                if router.hops is not None:
                    router.hops.finish(tid, "retire", replica=name)
                if self._stream_started:
                    # close OUR chunked response (the relay loop only
                    # forwards the replica's SSE lines)
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except OSError:
                        pass
                return

        # -- relay callbacks ---------------------------------------------

        def _relay_status(self, code: int, headers: dict,
                          data: bytes) -> None:
            self.send_response(code)
            tid = getattr(self, "_trace_id", None)
            if tid is not None and "x-cake-trace" not in headers:
                # successful non-stream responses get their trace id
                # too (the replica echoes it only on SSE and errors) —
                # every response through the front door hands the
                # client its federated-timeline key
                self.send_header("x-cake-trace", tid)
            for k, v in headers.items():
                self.send_header(k, v)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _relay_line(self, line: bytes) -> None:
            if not self._stream_started:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                # trace context back to the client: the trace id to
                # query the federated timeline with, plus the serving
                # replica and its echoed rid (on_admitted ran before
                # the first relayed line)
                tid = getattr(self, "_trace_id", None)
                if tid is not None:
                    self.send_header("x-cake-trace", tid)
                meta = getattr(self, "_sse_meta", None)
                if meta is not None:
                    self.send_header("x-cake-replica", meta[0])
                    if meta[1] is not None:
                        self.send_header("x-cake-rid", str(meta[1]))
                self.end_headers()
                self._stream_started = True
            self.wfile.write(hex(len(line))[2:].encode() + b"\r\n")
            self.wfile.write(line + b"\r\n")
            self.wfile.flush()

        def _terminal_error(self, message: str,
                            replica: Optional[str] = None) -> None:
            # the replica attribution rides the EVENT PAYLOAD, not
            # only a header: a mid-stream death happens long after the
            # response headers shipped, so the payload is the only
            # place a streaming client can still learn WHICH replica
            # died (non-stream 429/503s carry x-cake-replica instead)
            err = {"message": message, "type": "ReplicaDownError",
                   "retryable": True}
            if replica is not None:
                err["replica"] = replica
            tid = getattr(self, "_trace_id", None)
            if tid is not None:
                err["trace"] = tid
            payload = (b"data: " + json.dumps({"error": err}).encode()
                       + b"\n\n")
            try:
                if not self._stream_started:
                    # should not happen (midstream implies bytes went
                    # out), but never write a bare payload without
                    # headers
                    self.send_response(502)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length",
                                     str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                self.wfile.write(
                    hex(len(payload))[2:].encode() + b"\r\n")
                self.wfile.write(payload + b"\r\n")
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except OSError:
                pass   # client is gone too; nothing to tell anyone

    return Handler


def start_router(replicas, address: str = "127.0.0.1:10127",
                 block: bool = True, **router_kwargs):
    """Bind and serve the front door. Returns (httpd, router); with
    block=False the server runs on a daemon thread (tests, bench)."""
    host, port = address.rsplit(":", 1)
    router = RouterServer(replicas, **router_kwargs)
    router.tracker.start()
    if router.sentinel is not None:
        router.sentinel.start()
    if router.discovery is not None:
        router.discovery.start()
    httpd = ThreadingHTTPServer((host, int(port)),
                                make_router_handler(router))
    log.info("router listening on %s over replicas %s%s", address,
             ",".join(router.tracker.names()) or "(none yet)",
             ("; announce channel on port %d" % router.discovery.port
              if router.discovery is not None else ""))

    def serve():
        try:
            httpd.serve_forever()
        finally:
            router.close()

    if block:
        serve()
    else:
        threading.Thread(target=serve, daemon=True).start()
    return httpd, router
