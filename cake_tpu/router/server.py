"""The router HTTP front door.

`cake-tpu --router --replicas host:port,host:port,...` runs THIS
process role — no model, no devices: a ThreadingHTTPServer that routes
each chat request to one of N engine replicas (policy.py), proxies the
response through (proxy.py), and serves its own introspection:

  * POST /api/v1/chat/completions (+ /v1 alias) — routed + proxied
  * GET  /api/v1/router — replica states, policy mode, sticky keys
  * GET  /api/v1/health — the ROUTER's own health (cheap; replicas'
    health is what the tracker polls)
  * GET  /metrics — the cake_router_* families

Failover loop: a connect failure or a roamable refusal (draining 429,
switch 409, retryable 503) moves the request to the next pick until
every replica was tried; a shed/queue-full 429 relays VERBATIM with
the replica's computed Retry-After and x-cake-replica attribution. A
replica dying mid-stream surfaces as a terminal SSE error event; the
client's keyed reconnect (Last-Event-ID) re-routes — sticky to the
home replica while it lives, re-admitted elsewhere once it is ejected
(the engine-side fresh-admission Last-Event-ID suppression keeps the
resumed stream exact-suffix).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.router.affinity import (
    HashRing, prefix_fingerprint, text_fingerprint,
)
# _FAILOVERS is single-sourced in policy.py (which increments it for
# sticky home_ejected re-homes); a second declaration here would have
# to keep its help string byte-identical forever
from cake_tpu.router.policy import (
    _FAILOVERS, NoReplicaError, RoutingPolicy,
)
from cake_tpu.router.proxy import ReplicaProxy
from cake_tpu.router.replicas import ReplicaTracker

log = logging.getLogger(__name__)

_REQUESTS = obs_metrics.counter(
    "cake_router_requests_total",
    "Chat requests proxied, by backend replica and priority class",
    labelnames=("replica", "class"))
_SHEDS = obs_metrics.counter(
    "cake_router_sheds_total",
    "Requests the router could not place (no_replica) or relayed a "
    "replica refusal for (relay)", labelnames=("reason",))

DEFAULT_PAGE_SIZE = 128


class RouterServer:
    """Routing + proxy state shared by the handler threads."""

    # cakelint guards discipline: the tokenizer (page-aligned affinity
    # keys) and the decision JSONL log are both optional planes
    OPTIONAL_PLANES = ("tokenizer", "_log")

    def __init__(self, replicas, tokenizer=None,
                 poll_interval_s: float = 0.25,
                 stale_after_s: float = 2.0,
                 load_watermark: int = 8,
                 policy_mode: str = "affinity",
                 fetch=None, decision_log: Optional[str] = None,
                 vnodes: int = 64):
        self.tokenizer = tokenizer
        self.tracker = ReplicaTracker(
            replicas, poll_interval_s=poll_interval_s,
            stale_after_s=stale_after_s, fetch=fetch)
        self.ring = HashRing(self.tracker.names(), vnodes=vnodes)
        self.policy = RoutingPolicy(
            self.tracker, ring=self.ring,
            load_watermark=load_watermark, mode=policy_mode)
        self.proxy = ReplicaProxy()
        self._log = None
        if decision_log:
            from cake_tpu.obs.jsonl import JsonlAppender
            self._log = JsonlAppender(decision_log)
        if tokenizer is None:
            log.warning(
                "router: no tokenizer — affinity keys fall back to "
                "system-prompt TEXT fingerprints (stable, but not "
                "page-aligned; pass the model's tokenizer for the "
                "register_prefix rounding rule)")

    # -- affinity keys ---------------------------------------------------

    def _page_size(self) -> int:
        """The fleet's kv page size, read from any polled replica's
        lite health (replicas of one deployment share a config);
        default when nothing has reported one yet."""
        for st in self.tracker.states():
            if st.page_size:
                return int(st.page_size)
        return DEFAULT_PAGE_SIZE

    def affinity_key(self, body: dict) -> Optional[str]:
        """The request's shareable-head fingerprint: the rendered
        system-message head (exactly what the engine's --auto-prefix
        registers), page-aligned through the tokenizer when one is
        available."""
        msgs = body.get("messages") or []
        if not msgs or not isinstance(msgs[0], dict):
            return None
        if str(msgs[0].get("role", "")).lower() != "system":
            return None
        from cake_tpu.models.chat import BEGIN_OF_TEXT, History, Message
        try:
            head = BEGIN_OF_TEXT + History.encode_message(
                Message.from_json(msgs[0]))
        except (ValueError, AttributeError):
            return None
        if self.tokenizer is None:
            return text_fingerprint(head)
        from cake_tpu.models.llama.generator import encode_text
        ids = encode_text(self.tokenizer, head)
        return prefix_fingerprint(ids, self._page_size())

    # -- introspection ---------------------------------------------------

    def state(self) -> dict:
        return {
            "role": "router",
            "policy": self.policy.mode,
            "load_watermark": self.policy.load_watermark,
            "replicas": self.tracker.snapshot(),
            "page_size": self._page_size(),
            "affinity": ("paged" if self.tokenizer is not None
                         else "text"),
        }

    def health(self) -> dict:
        up = [s.name for s in self.tracker.admitting()]
        return {"status": "ok" if up else "degraded",
                "role": "router",
                "replicas_admitting": up,
                "replicas_total": len(self.tracker.names())}

    def note_decision(self, rec: dict) -> None:
        if self._log is not None:
            self._log.append(rec)

    def metrics(self) -> str:
        return obs_metrics.REGISTRY.render()

    def close(self) -> None:
        self.tracker.close()
        if self._log is not None:
            self._log.close()


def make_router_handler(router: RouterServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log.debug("router http: " + fmt, *args)

        def _json(self, code: int, obj: dict,
                  headers: Optional[dict] = None):
            data = json.dumps(obj).encode()
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            route = self.path.split("?", 1)[0]
            if route == "/api/v1/router":
                return self._json(200, router.state())
            if route == "/api/v1/health":
                return self._json(200, router.health())
            if route in ("/metrics", "/api/v1/metrics"):
                data = router.metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._json(404, {"error": "not found (router process: "
                                      "chat + router introspection "
                                      "only)"})

        def do_POST(self):
            route = self.path.split("?", 1)[0]
            if route not in ("/api/v1/chat/completions",
                             "/v1/chat/completions"):
                return self._json(404, {
                    "error": "not found (the router fronts chat "
                             "completions; administrative endpoints "
                             "live on the replicas)"})
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b"{}"
            try:
                body = json.loads(raw)
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as e:
                return self._json(400, {"error": f"invalid JSON body: "
                                                 f"{e}"})
            self._route_chat(route, raw, body)

        # -- routed chat -------------------------------------------------

        def _route_chat(self, route: str, raw: bytes,
                        body: dict) -> None:
            cls = body.get("priority") \
                or self.headers.get("x-cake-priority") or "standard"
            if not isinstance(cls, str):
                cls = "standard"
            idem = self.headers.get("x-cake-idempotency-key")
            stream = bool(body.get("stream"))
            try:
                key = router.affinity_key(body)
            except Exception:  # noqa: BLE001 — affinity is best-effort
                log.debug("affinity key failed", exc_info=True)
                key = None

            self._stream_started = False
            tried: set = set()
            last_refusal_ra = None
            while True:
                try:
                    decision = router.policy.route(
                        key=key, idem_key=idem, exclude=tried)
                except NoReplicaError as e:
                    _SHEDS.labels(reason="no_replica").inc()
                    router.note_decision({
                        "event": "shed", "class": cls,
                        "tried": sorted(tried)})
                    hdrs = {}
                    # a REPLICA-computed Retry-After only: the drain
                    # ETA from a lite-health doc, or the one carried
                    # by the last roamable refusal this very request
                    # saw — the router never invents its own
                    ra = (e.retry_after_s if e.retry_after_s is not None
                          else last_refusal_ra)
                    if ra is not None:
                        hdrs["Retry-After"] = str(
                            max(1, int(-(-ra // 1))))
                    return self._json(503, {
                        "error": "no replica available",
                        "tried": sorted(tried),
                        "retryable": True}, headers=hdrs)

                name = decision.replica

                def admitted(name=name):
                    # as soon as the replica 200s: the request holds a
                    # slot there, so keyed reconnects must find this
                    # home even while the stream is still running
                    _REQUESTS.labels(name, cls).inc()
                    router.policy.note_admitted(idem, name)

                outcome = router.proxy.forward_chat(
                    name, route, raw, self.headers, stream,
                    send_status=self._relay_status,
                    send_line=self._relay_line,
                    send_terminal_error=self._terminal_error,
                    on_admitted=admitted)
                router.note_decision({
                    "event": "route", "replica": name,
                    "outcome": decision.outcome, "class": cls,
                    "proxy": outcome.kind, "status": outcome.status})

                if outcome.kind == "retryable":
                    tried.add(name)
                    if outcome.retry_after_s is not None:
                        last_refusal_ra = outcome.retry_after_s
                    if outcome.hard:
                        # connect-level failure: strong evidence —
                        # eject now, probe later (the poller would
                        # take a staleness window to notice)
                        router.tracker.note_failure(name, hard=True)
                        _FAILOVERS.labels(reason="connect").inc()
                    else:
                        # post-connect: either a roamable REFUSAL
                        # (draining/switch/reset — a protocol answer
                        # from a live replica, no failure evidence) or
                        # a genuine break (header timeout, cut body —
                        # soft evidence: a busy replica is not a
                        # corpse). Labels stay bounded either way.
                        reason = (outcome.error if outcome.error in
                                  ("draining", "switch", "reset")
                                  else "replica_error")
                        if reason == "replica_error":
                            router.tracker.note_failure(name)
                        _FAILOVERS.labels(reason=reason).inc()
                    continue
                if outcome.kind == "midstream":
                    _FAILOVERS.labels(reason="midstream").inc()
                    router.tracker.note_failure(name)
                    return
                if outcome.kind == "relayed":
                    _SHEDS.labels(reason="relay").inc()
                    return
                # "ok": relay complete (admission was counted by the
                # on_admitted callback when the 200 arrived)
                if self._stream_started:
                    # close OUR chunked response (the relay loop only
                    # forwards the replica's SSE lines)
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except OSError:
                        pass
                return

        # -- relay callbacks ---------------------------------------------

        def _relay_status(self, code: int, headers: dict,
                          data: bytes) -> None:
            self.send_response(code)
            for k, v in headers.items():
                self.send_header(k, v)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _relay_line(self, line: bytes) -> None:
            if not self._stream_started:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                self._stream_started = True
            self.wfile.write(hex(len(line))[2:].encode() + b"\r\n")
            self.wfile.write(line + b"\r\n")
            self.wfile.flush()

        def _terminal_error(self, message: str) -> None:
            payload = (b"data: " + json.dumps({"error": {
                "message": message, "type": "ReplicaDownError",
                "retryable": True}}).encode() + b"\n\n")
            try:
                if not self._stream_started:
                    # should not happen (midstream implies bytes went
                    # out), but never write a bare payload without
                    # headers
                    self.send_response(502)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length",
                                     str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                self.wfile.write(
                    hex(len(payload))[2:].encode() + b"\r\n")
                self.wfile.write(payload + b"\r\n")
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except OSError:
                pass   # client is gone too; nothing to tell anyone

    return Handler


def start_router(replicas, address: str = "127.0.0.1:10127",
                 block: bool = True, **router_kwargs):
    """Bind and serve the front door. Returns (httpd, router); with
    block=False the server runs on a daemon thread (tests, bench)."""
    host, port = address.rsplit(":", 1)
    router = RouterServer(replicas, **router_kwargs)
    router.tracker.start()
    httpd = ThreadingHTTPServer((host, int(port)),
                                make_router_handler(router))
    log.info("router listening on %s over replicas %s", address,
             ",".join(router.tracker.names()))

    def serve():
        try:
            httpd.serve_forever()
        finally:
            router.close()

    if block:
        serve()
    else:
        threading.Thread(target=serve, daemon=True).start()
    return httpd, router
