"""Front-door router: prefix-affinity load balancing over N engine
replicas (ROADMAP item 2 — a replicated data plane).

One thin process in front of N independent `--api` engine servers:

  * `affinity.py` — consistent-hash ring keyed by the request's
    page-aligned prefix fingerprint (the same rounding rule as the
    paged engine's register_prefix), so every conversation sharing a
    system prompt lands on the replica that already holds its prefix
    pages — PR 4's per-engine prefix sharing becomes a fleet-level
    cache.
  * `replicas.py` — a per-replica poller of `GET /api/v1/health?lite=1`
    (queue depths, SLO attainment, autotune epoch, draining, breaker)
    with staleness-based ejection and jittered re-probe backoff.
  * `policy.py` — weighted pick: sticky idempotency keys, then
    affinity with a bounded-load spill, then least-loaded healthy.
  * `proxy.py` — streaming SSE pass-through preserving `id:` fields
    and Retry-After headers verbatim, with typed mid-stream error
    mapping.
  * `tracing.py` — router-side distributed tracing: per-request hop
    records (admit, pick + affinity verdict, connect, first byte,
    failover resume, retire) keyed by the minted/propagated
    `x-cake-trace` id; the front-door half of the federated
    `GET /api/v1/requests/{rid}/timeline`.
  * `discovery.py` — fleet discovery (`--router-announce`): replicas
    self-register over a token-gated announce channel (the PR 11
    telemetry framing), pushed frames supersede polling while fresh,
    departures drain-then-forget, and pushed headroom/attainment
    compose into placement weight factors with provenance
    (`GET /api/v1/fleet`).
  * `server.py` — the HTTP front door (`cake-tpu --router
    --replicas host:port,...`) with the router-tier event ring,
    federated timeline endpoint and `--sentinel` anomaly detectors
    (obs/sentinel.py).
"""

from cake_tpu.router.affinity import (          # noqa: F401
    HashRing, prefix_fingerprint, text_fingerprint,
)
from cake_tpu.router.discovery import (         # noqa: F401
    AnnounceListener, FleetDiscovery, ReplicaAnnouncer,
)
from cake_tpu.router.policy import NoReplicaError, RoutingPolicy  # noqa: F401
from cake_tpu.router.replicas import ReplicaState, ReplicaTracker  # noqa: F401
from cake_tpu.router.server import RouterServer, start_router  # noqa: F401
from cake_tpu.router.tracing import HopRecord, HopTracer  # noqa: F401
