"""OpenAI chat-completions wire shapes.

Matches the reference's request/response structs (api/text.rs:11-52): request
{messages: [{role, content}]}, response a `chat.completion` object with uuid
id, unix timestamp, single choice. Streaming responses use the standard
`chat.completion.chunk` SSE shape (an upgrade — the reference buffers,
api/text.rs:80-95).
"""

from __future__ import annotations

import time
import uuid
from typing import List, Optional

from cake_tpu.models.chat import Message


def parse_chat_request(body: dict) -> tuple[List[Message], dict]:
    """Extract messages + generation options from a request body."""
    msgs = [Message.from_json(m) for m in body.get("messages", [])]
    opts = {
        "stream": bool(body.get("stream", False)),
        "max_tokens": body.get("max_tokens"),
        "temperature": body.get("temperature"),
        "top_p": body.get("top_p"),
        "logprobs": bool(body.get("logprobs", False)),
        "top_logprobs": body.get("top_logprobs"),
        # SLO scheduling class (cake_tpu/sched): request-body
        # "priority" wins over the x-cake-priority header (the handler
        # folds the header in before parsing); None = standard
        "priority": body.get("priority"),
    }
    if opts["priority"] is not None:
        from cake_tpu.sched.classes import validate_priority
        if not isinstance(opts["priority"], str):
            raise ValueError("priority must be a string")
        validate_priority(opts["priority"])   # unknown -> ValueError -> 400
    if opts["top_logprobs"] is not None:
        n = opts["top_logprobs"]
        if (not isinstance(n, int) or isinstance(n, bool)
                or not (0 <= n <= 20)):
            raise ValueError("top_logprobs must be an integer in [0, 20]")
        if not opts["logprobs"]:
            raise ValueError("top_logprobs requires logprobs: true")
    return msgs, opts


def completion_response(text: str, model: str = "cake-tpu",
                        logprobs: list | None = None) -> dict:
    """logprobs: optional [{"token": str, "logprob": float}] content list
    (OpenAI `logprobs: true`; non-streaming responses only)."""
    choice = {
        "index": 0,
        "message": {"role": "assistant", "content": text},
        "finish_reason": "stop",
        # OpenAI schema: logprobs is null unless requested
        "logprobs": ({"content": logprobs}
                     if logprobs is not None else None),
    }
    return {
        "id": str(uuid.uuid4()),
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [choice],
    }


def chunk_response(delta: str, model: str = "cake-tpu",
                   finish: Optional[str] = None, rid: str = "",
                   logprobs: Optional[list] = None) -> dict:
    """logprobs: optional list of per-token content entries covering the
    tokens that produced this delta (OpenAI streaming `logprobs` shape:
    choices[0].logprobs.content)."""
    return {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "delta": {} if finish else {"content": delta},
            "logprobs": ({"content": logprobs}
                         if logprobs is not None else None),
            "finish_reason": finish,
        }],
    }
