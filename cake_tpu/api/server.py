"""Threaded HTTP server exposing the OpenAI-compatible API.

Reference behavior (api/mod.rs, api/text.rs, api/image.rs): the master is
shared state; the text endpoint resets chat state, appends the request
messages, runs the full generation, returns one JSON completion; the image
endpoint returns base64 PNGs; unknown routes 404.

Differences (deliberate upgrades, SURVEY.md §7.4):
  * `"stream": true` streams SSE `chat.completion.chunk`s token-by-token —
    the reference computes tokens incrementally but buffers the HTTP body.
  * Requests queue on an explicit generation lock with a `Retry-After` 503
    once the queue is deep, instead of silently serialising on a RwLock.
  * GET /api/v1/health and /api/v1/cluster expose device/topology
    introspection (the reference's WorkerInfo, proto/message.rs:42-58,
    becomes JAX device queries).
"""

from __future__ import annotations

import json
import logging
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from cake_tpu.api.openai import (
    chunk_response, completion_response, parse_chat_request,
)
from cake_tpu.args import ImageGenerationArgs

log = logging.getLogger(__name__)

MAX_WAITING = 16


class ApiServer:
    """Wraps a Master; one generation at a time, queued fairly."""

    def __init__(self, master, model_name: str = "cake-tpu"):
        self.master = master
        self.model_name = model_name
        self._gen_lock = threading.Lock()
        self._waiting = 0
        self._waiting_lock = threading.Lock()

    # -- text ---------------------------------------------------------------

    def chat(self, body: dict, send_chunk=None,
             on_start=None) -> Optional[dict]:
        """Run one chat completion. If send_chunk is set, stream deltas
        through it and return None; else return the full response dict.
        `on_start` fires after admission + the generation lock are held and
        before any tokens — the streaming handler sends its response headers
        there, so queue rejections still surface as a clean 503."""
        messages, opts = parse_chat_request(body)
        with self._admission():
            with self._gen_lock:
                m = self.master
                m.reset()
                if m.llm is not None and hasattr(m.llm, "set_sampling"):
                    m.llm.set_sampling(temperature=opts["temperature"],
                                       top_p=opts["top_p"])
                for msg in messages:
                    m.add_message(msg)
                rid = str(uuid.uuid4())
                if send_chunk is None:
                    text = m.generate_text(lambda t: None,
                                           sample_len=opts["max_tokens"])
                    return completion_response(text, self.model_name)
                if on_start is not None:
                    on_start()
                m.generate_text(
                    lambda t: send_chunk(
                        chunk_response(t.text, self.model_name, rid=rid)),
                    sample_len=opts["max_tokens"],
                )
                send_chunk(chunk_response("", self.model_name,
                                          finish="stop", rid=rid))
                return None

    # -- image --------------------------------------------------------------

    def image(self, body: dict) -> dict:
        import base64
        args = ImageGenerationArgs.from_json(body)
        images: list = []
        with self._admission():
            with self._gen_lock:
                self.master.generate_image(
                    args, lambda pngs: images.extend(pngs))
        return {"images": [base64.b64encode(p).decode() for p in images]}

    # -- introspection -------------------------------------------------------

    def health(self) -> dict:
        return {"status": "ok", "model": self.model_name,
                "queue_depth": self._waiting}

    def cluster(self) -> dict:
        import jax
        return {
            "devices": [
                {"id": d.id, "platform": d.platform,
                 "kind": d.device_kind, "process": d.process_index}
                for d in jax.devices()
            ],
        }

    # -- admission -----------------------------------------------------------

    def _admission(self):
        server = self

        class _Adm:
            def __enter__(self):
                with server._waiting_lock:
                    if server._waiting >= MAX_WAITING:
                        raise QueueFull()
                    server._waiting += 1

            def __exit__(self, *exc):
                with server._waiting_lock:
                    server._waiting -= 1
        return _Adm()


class QueueFull(Exception):
    pass


def make_handler(api: ApiServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log.debug("http: " + fmt, *args)

        def _json(self, code: int, obj: dict):
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _read_body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            if n == 0:
                return {}
            try:
                return json.loads(self.rfile.read(n))
            except json.JSONDecodeError:
                raise ValueError("invalid JSON body")

        def do_GET(self):
            if self.path == "/api/v1/health":
                return self._json(200, api.health())
            if self.path == "/api/v1/cluster":
                return self._json(200, api.cluster())
            self._json(404, {"error": "not found"})  # api/mod.rs:19-21

        def do_POST(self):
            try:
                body = self._read_body()
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            try:
                if self.path == "/api/v1/chat/completions":
                    return self._chat(body)
                if self.path == "/api/v1/image":
                    return self._json(200, api.image(body))
                return self._json(404, {"error": "not found"})
            except QueueFull:
                if getattr(self, "_stream_started", False):
                    return  # headers already gone; just drop the connection
                data = json.dumps({"error": "queue full"}).encode()
                self.send_response(503)
                self.send_header("Retry-After", "1")
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except Exception as e:  # noqa: BLE001
                log.exception("request failed")
                if getattr(self, "_stream_started", False):
                    return
                self._json(500, {"error": f"{type(e).__name__}: {e}"})

        def _chat(self, body: dict):
            if not body.get("stream"):
                return self._json(200, api.chat(body))
            self._stream_started = False

            def on_start():
                # only once admission + the generation lock are held
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                self._stream_started = True

            def send_chunk(obj: dict):
                payload = f"data: {json.dumps(obj)}\n\n".encode()
                self.wfile.write(hex(len(payload))[2:].encode() + b"\r\n")
                self.wfile.write(payload + b"\r\n")
                self.wfile.flush()

            api.chat(body, send_chunk=send_chunk, on_start=on_start)
            done = b"data: [DONE]\n\n"
            self.wfile.write(hex(len(done))[2:].encode() + b"\r\n")
            self.wfile.write(done + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")

    return Handler


def start(master, address: str = "127.0.0.1:10128",
          model_name: str = "cake-tpu", block: bool = True):
    """Bind and serve (reference api/mod.rs:23-48)."""
    host, port = address.rsplit(":", 1)
    api = ApiServer(master, model_name)
    httpd = ThreadingHTTPServer((host, int(port)), make_handler(api))
    log.info("REST API listening on %s", address)
    if block:
        httpd.serve_forever()
    else:
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
    return httpd
