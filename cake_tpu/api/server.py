"""Threaded HTTP server exposing the OpenAI-compatible API.

Reference behavior (api/mod.rs, api/text.rs, api/image.rs): the master is
shared state; the text endpoint resets chat state, appends the request
messages, runs the full generation, returns one JSON completion; the image
endpoint returns base64 PNGs; unknown routes 404.

Differences (deliberate upgrades, SURVEY.md §7.4):
  * `"stream": true` streams SSE `chat.completion.chunk`s token-by-token —
    the reference computes tokens incrementally but buffers the HTTP body.
  * Requests queue on an explicit generation lock with a `Retry-After` 503
    once the queue is deep, instead of silently serialising on a RwLock.
  * GET /api/v1/health and /api/v1/cluster expose device/topology
    introspection (the reference's WorkerInfo, proto/message.rs:42-58,
    becomes JAX device queries).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from cake_tpu.api.openai import (
    chunk_response, completion_response, parse_chat_request,
)
from cake_tpu.args import ImageGenerationArgs
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import steps as obs_steps
from cake_tpu.obs import tracing as obs_tracing
from cake_tpu.serve.errors import EngineRequestError

log = logging.getLogger(__name__)

MAX_WAITING = 16

# routes worth a per-route counter series; anything else (scanners,
# typos) collapses into "other" so a 404 spray cannot explode the label
# cardinality
KNOWN_ROUTES = frozenset({
    "/api/v1/chat/completions", "/v1/chat/completions", "/api/v1/image",
    "/api/v1/health", "/api/v1/cluster", "/v1/models", "/api/v1/models",
    "/metrics", "/api/v1/metrics", "/api/v1/requests", "/api/v1/steps",
    "/api/v1/profile", "/api/v1/autotune", "/api/v1/events",
    "/api/v1/requests/{rid}/timeline", "/api/v1/fleet",
    "/api/v1/drain", "/api/v1/anomalies",
})

# rid-bearing paths are counted under their TEMPLATE: a per-rid route
# label would grow one metric series per request — exactly the
# cardinality explosion tools/lint_metrics.py bans rid labels for
_TIMELINE_RE = re.compile(r"^/api/v1/requests/(\d+)/timeline$")


class ApiServer:
    """Wraps a Master. With an engine, chat requests batch continuously —
    N requests decode together in one batched program; without one, they
    serialise on a generation lock (still an upgrade over the reference's
    silent RwLock, api/text.rs:67)."""

    # cakelint guards discipline: the federation collector is optional
    # (coordinator-with---telemetry-collect only)
    OPTIONAL_PLANES = ("collector",)

    def __init__(self, master, model_name: str = "cake-tpu", engine=None,
                 health=None, collector=None, replica_id=None):
        import os
        import socket
        self.master = master
        self.model_name = model_name
        self.engine = engine
        # stable id for THIS serving process, so a front-door router
        # (cake_tpu/router) and clients can attribute backpressure to a
        # specific replica: the x-cake-replica header on 429/503
        # responses and the `replica` health field both carry it.
        # start() passes the bind address; CAKE_REPLICA_ID overrides.
        self.replica_id = (replica_id
                           or os.environ.get("CAKE_REPLICA_ID")
                           or socket.gethostname())
        # last page size read under a successful non-blocking
        # _switch_lock acquire (see _page_size)
        self._page_size_cache = None
        # parallel.health.ServingHealth: when it flips to failed, chat
        # requests 503 and /api/v1/health reports the reason
        self.health_state = health
        # obs/federation.TelemetryCollector (multi-host serving): the
        # fleet endpoint, ?host= event filters and the host-labeled
        # federated /metrics families all read from it; attaching it to
        # the engine makes request timelines span hosts
        self.collector = collector
        if collector is not None and engine is not None:
            engine.telemetry = collector
        if engine is not None:
            engine.start()
        self._gen_lock = threading.Lock()
        self._waiting = 0
        self._waiting_lock = threading.Lock()
        # drain plumbing (POST /api/v1/drain): start() wires _shutdown
        # to its save-and-exit closure; the drain thread calls it once
        # in-flight work finishes (or the drain timeout expires)
        self._shutdown = None
        self._drain_thread = None
        self._drain_lock = threading.Lock()
        self.started_at = int(time.time())  # /v1/models "created"
        # POST /api/v1/profile capture target (--profile-dir; None =
        # a fresh temp dir per capture)
        self._profile_dir = getattr(
            getattr(master, "args", None), "profile_dir", None)
        self._m_http = obs_metrics.counter(
            "cake_http_requests_total",
            "HTTP requests served, by route and status code",
            labelnames=("route", "status"))

    def _count(self, path: str, code: int) -> None:
        route = path.split("?", 1)[0]
        if _TIMELINE_RE.match(route):
            route = "/api/v1/requests/{rid}/timeline"
        if route not in KNOWN_ROUTES:
            route = "other"
        self._m_http.labels(route=route, status=str(code)).inc()

    # -- text ---------------------------------------------------------------

    def chat(self, body: dict, send_chunk=None, on_start=None,
             idempotency_key=None, last_event_id=None,
             trace_id=None) -> Optional[dict]:
        """Run one chat completion. If send_chunk is set, stream deltas
        through it and return None; else return the full response dict.
        `on_start` fires after admission and before any tokens — the
        streaming handler sends its response headers there, so queue
        rejections still surface as a clean 503; a callback accepting
        `rid=` additionally receives the engine rid (the handler echoes
        it as x-cake-rid, the front-door router's trace join key).

        idempotency_key (x-cake-idempotency-key): a retried submit with
        the same key attaches to the live/finished stream instead of
        double-admitting — safe client retry, across restarts too when
        --journal is armed. last_event_id (Last-Event-ID): on a
        streaming reconnect, replay the journaled/held suffix after
        that absolute token id, then continue live. trace_id
        (x-cake-trace): the originating distributed-trace id, threaded
        to the engine tracer/event bus at admission."""
        if self.engine is not None:
            return self._chat_engine(body, send_chunk, on_start,
                                     idempotency_key=idempotency_key,
                                     last_event_id=last_event_id,
                                     trace_id=trace_id)
        if idempotency_key is not None or last_event_id is not None:
            raise ValueError(
                "idempotency keys / Last-Event-ID resume require the "
                "batching engine (this deployment serves through the "
                "legacy locked path)")
        messages, opts = parse_chat_request(body)
        if opts.get("logprobs"):
            raise ValueError(
                "logprobs requires the batching engine (this deployment "
                "serves through the legacy locked path)")
        # clamp to the serving mode's decode budget (e.g. the --sp
        # adapter's replicated tail): generating past it raises mid-
        # stream, after headers are gone — the client would hang on a
        # never-terminated chunked response
        budget = getattr(getattr(self.master.llm, "_forward_fn", None),
                         "max_decode_tokens", None)
        if budget is not None:
            opts["max_tokens"] = min(opts["max_tokens"] or budget, budget)
        t0 = time.perf_counter()
        with self._admission():
            with self._gen_lock:
                m = self.master
                m.reset()
                if m.llm is not None and hasattr(m.llm, "set_sampling"):
                    m.llm.set_sampling(temperature=opts["temperature"],
                                       top_p=opts["top_p"])
                for msg in messages:
                    m.add_message(msg)
                rid = str(uuid.uuid4())
                if send_chunk is None:
                    text = m.generate_text(lambda t: None,
                                           sample_len=opts["max_tokens"])
                    # locked-path e2e latency: the engine path records
                    # this through its tracer; here the handler is the
                    # only seam that sees the whole request
                    obs_tracing.REQUEST_E2E.observe(
                        time.perf_counter() - t0)
                    return completion_response(text, self.model_name)
                if on_start is not None:
                    on_start()
                m.generate_text(
                    lambda t: send_chunk(
                        chunk_response(t.text, self.model_name, rid=rid)),
                    sample_len=opts["max_tokens"],
                )
                send_chunk(chunk_response("", self.model_name,
                                          finish="stop", rid=rid))
                obs_tracing.REQUEST_E2E.observe(time.perf_counter() - t0)
                return None

    def _chat_engine(self, body: dict, send_chunk=None,
                     on_start=None, idempotency_key=None,
                     last_event_id=None,
                     trace_id=None) -> Optional[dict]:
        """Continuous-batching path: no lock — the engine interleaves this
        request's decode steps with every other in-flight request."""
        from cake_tpu.serve.engine import QueueFullError
        messages, opts = parse_chat_request(body)
        want_lp = bool(opts.get("logprobs"))
        n_top = opts.get("top_logprobs") or 0
        kw = dict(
            max_new_tokens=opts["max_tokens"] or self.master.args.sample_len,
            temperature=opts["temperature"],
            top_p=opts["top_p"],
            want_top_logprobs=n_top > 0,
            priority=opts.get("priority"),
            idempotency_key=idempotency_key,
            trace_id=trace_id,
        )

        def lp_entry(t, lp, top):
            text = self.engine.tokenizer.decode([t])
            e = {"token": text, "logprob": round(lp, 6),
                 "bytes": list(text.encode()), "top_logprobs": []}
            if n_top:
                def alt(at, al):
                    atext = self.engine.tokenizer.decode([at])
                    return {"token": atext, "logprob": round(al, 6),
                            "bytes": list(atext.encode())}
                e["top_logprobs"] = [alt(at, al) for at, al in top[:n_top]]
            return e

        from cake_tpu.sched import ShedError
        from cake_tpu.serve.errors import DrainingError

        if send_chunk is None:
            try:
                h = self.engine.chat(messages, **kw)
            except (QueueFullError, ShedError) as e:
                raise QueueFull(getattr(e, "retry_after", 1.0),
                                shed=isinstance(e, ShedError))
            except DrainingError as e:
                raise QueueFull(e.retry_after, draining=True)
            h.wait()
            lp = None
            if want_lp:
                lp = [lp_entry(t, l, top) for (t, l), top
                      in zip(h.token_logprobs, h.token_top_logprobs)]
            text = h.text()   # raises the typed error if the engine failed it
            rep = list(getattr(h._req, "replayed_tokens", ()) or ())
            if rep:
                # a journal/checkpoint-resumed stream: the client's
                # transcript is the WHOLE generation — the tokens
                # replayed from previous process generations plus this
                # epoch's (h.text() alone covers only the latter)
                eos = self.engine.config.eos_token_ids
                text = self.engine.tokenizer.decode(
                    [t for t in rep + list(h._req.out_tokens)
                     if t not in eos])
            return completion_response(text, self.model_name,
                                       logprobs=lp)

        rid = str(uuid.uuid4())
        # Deltas are queued by the engine thread and written here on the
        # handler thread: a slow client must never block the engine loop
        # (that would stall every other in-flight request).
        import queue as _queue
        deltas: _queue.Queue = _queue.Queue()

        def stream(delta: str, final: bool, n_done: int = 0):
            deltas.put((delta, final, n_done))

        # wants_count: the engine snapshots the finalized-entry count on
        # the engine thread at emit time, so each chunk's logprob entries
        # pair exactly with the delta carrying their text (a held-back
        # UTF-8 tail token's entry ships with the later chunk that
        # contains its text, never ahead of it)
        stream.wants_count = True
        # back-compat with 1-arg send_chunk callables (embedders,
        # tests): only a callback that accepts event_id gets the SSE
        # resume ids; others receive plain chunks
        _wants_id = _accepts_kwarg(send_chunk, "event_id")
        raw_send = send_chunk

        def send_chunk(obj, event_id=None):
            if _wants_id and event_id is not None:
                raw_send(obj, event_id=event_id)
            else:
                raw_send(obj)

        try:
            h = self.engine.chat(messages, stream=stream, **kw)
        except (QueueFullError, ShedError) as e:
            raise QueueFull(getattr(e, "retry_after", 1.0),
                            shed=isinstance(e, ShedError))
        except DrainingError as e:
            raise QueueFull(e.retry_after, draining=True)
        if on_start is not None:
            # a callback accepting rid= gets the engine rid (the
            # handler echoes it as x-cake-rid before any tokens, so a
            # front-door router learns the trace join key at
            # admission); plain zero-arg callbacks (embedders, tests)
            # keep working
            if _accepts_kwarg(on_start, "rid"):
                on_start(rid=h._req.rid)
            else:
                on_start()
        lp_cursor = 0
        eos_ids = self.engine.config.eos_token_ids
        r = h._req
        # SSE event ids are ABSOLUTE token positions: tokens replayed
        # from previous process generations count, so a client's
        # Last-Event-ID survives any number of restarts
        id_base = len(getattr(r, "replayed_tokens", ()) or ())
        sent_id = id_base   # high-water mark of delivered event ids

        def chunk_lp(upto):
            nonlocal lp_cursor
            if not want_lp:
                return None
            entries = [
                lp_entry(r.out_tokens[i], r.out_logprobs[i], r.out_top[i])
                for i in range(lp_cursor, upto)
                if r.out_tokens[i] not in eos_ids
            ]
            lp_cursor = upto
            return entries

        # trim_from: set when a FRESH admission arrives with a
        # Last-Event-ID (the front-door router failing a keyed stream
        # over to a different replica, which re-runs the whole prompt
        # deterministically): events at or below the client's high-water
        # mark are suppressed, and the first batch crossing it re-decodes
        # only the unseen token suffix — the attach path's exact-suffix
        # semantics, without a local attach to replay from. Same text
        # re-decode boundary caveat as the attach replay.
        trim_from = None
        if getattr(h, "attached", False):
            # idempotent reconnect: replay the held/journaled suffix
            # after the client's Last-Event-ID as ONE chunk (its id is
            # the absolute position it covers up to), then fall into
            # the live loop — queued deltas at or below the replayed
            # high-water mark are dropped there, so the client sees
            # exactly the missing tokens: no duplicates, no gaps.
            history = (list(getattr(r, "replayed_tokens", ()) or ())
                       + list(r.out_tokens))
            start_at = max(0, int(last_event_id or 0))
            suffix = [t for t in history[start_at:]
                      if t not in eos_ids]
            try:
                if suffix:
                    send_chunk(chunk_response(
                        self.engine.tokenizer.decode(suffix),
                        self.model_name, rid=rid),
                        event_id=len(history))
            except OSError:
                return DISCONNECTED   # reconnect died mid-replay
            sent_id = max(start_at, len(history))
            lp_cursor = max(0, sent_id - id_base)
        elif last_event_id:
            # fresh admission, resuming client: suppress what it holds
            sent_id = max(sent_id, int(last_event_id))
            lp_cursor = max(0, sent_id - id_base)
            trim_from = lp_cursor

        while True:
            try:
                delta, final, n_done = deltas.get(timeout=0.5)
            except _queue.Empty:
                if h._req.done.is_set() and deltas.empty():
                    break  # request ended without a final delta (error path)
                continue
            ev_id = id_base + n_done
            if delta and ev_id > sent_id:
                if trim_from is not None:
                    # the batch crossing the resumed client's
                    # Last-Event-ID: ship only the unseen suffix
                    toks = [t for t in r.out_tokens[trim_from:n_done]
                            if t not in eos_ids]
                    delta = (self.engine.tokenizer.decode(toks)
                             if toks else "")
                    trim_from = None
                    if not delta:
                        # the whole crossing batch was EOS/empty:
                        # nothing to write, but the position advances
                        sent_id = ev_id
                        if final:
                            break
                        continue
                try:
                    send_chunk(chunk_response(delta, self.model_name,
                                              rid=rid,
                                              logprobs=chunk_lp(n_done)),
                               event_id=ev_id)
                    sent_id = ev_id
                except OSError:
                    # client disconnected mid-stream: free the slot now
                    # instead of decoding to max_tokens for nobody —
                    # UNLESS the request is idempotency-keyed: the
                    # client told us it will reconnect and resume, so
                    # the stream keeps decoding for its return
                    if r.idempotency_key is None:
                        log.info("client disconnected; cancelling "
                                 "request")
                        self.engine.cancel(h)
                    else:
                        log.info("client disconnected; rid=%d keeps "
                                 "decoding for an idempotent reconnect",
                                 r.rid)
                    return DISCONNECTED
            if final:
                break
        try:
            h.text()  # raises if the engine failed the request
        except Exception as e:  # noqa: BLE001
            # the headers are long gone: an open SSE stream gets a
            # TERMINAL error event (typed + retryable flag) instead of
            # a silent close the client cannot tell from success
            try:
                send_chunk({"error": {
                    "message": str(e), "type": type(e).__name__,
                    "retryable": bool(getattr(e, "retryable", False)),
                }})
            except OSError:
                return DISCONNECTED
            return None
        try:
            # the finish chunk flushes entries finalized after the last
            # text-bearing delta (e.g. an EOS-terminated request whose
            # final delta was empty), keeping the one-entry-per-token
            # contract; the request is done, so the full lists are stable
            send_chunk(chunk_response("", self.model_name,
                                      finish="stop", rid=rid,
                                      logprobs=chunk_lp(
                                          len(h._req.out_tokens))),
                       event_id=id_base + len(h._req.out_tokens))
        except OSError:
            return DISCONNECTED  # request already complete; just stop
        return None

    # -- image --------------------------------------------------------------

    def image(self, body: dict) -> dict:
        import base64
        args = ImageGenerationArgs.from_json(body)
        images: list = []
        with self._admission():
            with self._gen_lock:
                self.master.generate_image(
                    args, lambda pngs: images.extend(pngs))
        return {"images": [base64.b64encode(p).decode() for p in images]}

    # -- introspection -------------------------------------------------------

    def _page_size(self):
        """The paged engine's kv page size (None for dense) — the
        router aligns its affinity fingerprints to it (the
        register_prefix rounding rule)."""
        eng = self.engine
        if eng is None or not getattr(eng, "paged", False):
            return None
        # the pager swaps wholesale during a live reconfigure; its
        # declared lock pins one consistent value. NON-blocking on
        # purpose (the refresh_page_gauges discipline): the health
        # endpoint — including the router's sub-second lite poll —
        # must never stall behind a fold-everything switch holding
        # the lock through jit compiles, or the router would eject a
        # healthy replica exactly when it is switching. On contention
        # the last-seen value serves one more poll.
        if eng._switch_lock.acquire(blocking=False):
            try:
                # cakelint: skip[affinity] _switch_lock held via the non-blocking acquire above (the with-form would block the health path behind a wedged switch)
                self._page_size_cache = eng._pager.page_size
            finally:
                eng._switch_lock.release()
        return self._page_size_cache

    def health(self, lite: bool = False) -> dict:
        """/api/v1/health. lite (?lite=1): ONLY the fields a front-door
        router polls every few hundred ms — queue depths, SLO
        attainment, config epoch, draining, breaker — each a SUBTREE of
        the full document (pinned by contract test). The full document
        walks every subsystem (journal state, recovery wire state,
        lifetime counters): too heavy for a 250ms poll loop."""
        failed = (self.health_state is not None
                  and self.health_state.failed)
        out = {"status": "failed" if failed else "ok",
               "replica": self.replica_id,
               # doc build-time wall clock: the router's per-replica
               # clock-offset estimate (min over polls of receive-wall
               # minus this) — the federated timeline's correction
               # input, same rule as obs/federation.py frames
               "now": round(time.time(), 6),
               "queue_depth": self._waiting}
        if not lite:
            out["model"] = self.model_name
        if failed:
            out["reason"] = self.health_state.reason
        if self.engine is None:
            return out
        eng = self.engine
        out.update(
            queue_depth=eng.queue_depth,
            active_requests=eng.active,
            decode_slots=eng.max_slots,
        )
        depths = getattr(eng.scheduler, "class_depths", None)
        if depths is not None:
            # SLO scheduling on: per-class queue depths
            out["queue_depth_by_class"] = depths()
        if getattr(eng, "_draining", False):
            # drain in flight (POST /api/v1/drain / SIGTERM):
            # admissions 429 while this block counts down the
            # remaining in-flight work
            out["draining"] = True
            out["drain"] = eng.drain_state()
        ps = self._page_size()
        if ps is not None:
            out["page_size"] = ps
        if hasattr(eng, "current_config"):
            # the autotune epoch + switch flag: a router redirects
            # fresh admissions while a fold-everything switch runs
            out["config_epoch"] = getattr(eng, "config_epoch", 0)
            out["autotune"] = getattr(eng, "autotune_mode", "off")
            out["switch_in_flight"] = bool(
                getattr(eng, "_switch_inflight", False))
        slo = getattr(eng, "slo", None)
        if slo is not None:
            # serving quality (obs/slo.py): the router's weighted pick
            # reads attainment; the full doc carries the whole snapshot
            if lite:
                out["slo"] = {"attainment_1m": {
                    c: round(v, 4) for c, v in
                    slo.attainment_by_class("1m").items()}}
            else:
                out["slo"] = slo.snapshot()
        if hasattr(eng, "recovery_state"):
            if lite:
                # just the breaker bit (a tripped breaker means this
                # replica is a restart away — stop routing to it); the
                # full recovery_state walks the fault plan and control
                # wire state
                out["recovery"] = {"breaker": {"tripped": bool(
                    getattr(eng, "_breaker_tripped", False))}}
            else:
                out["recovery"] = eng.recovery_state()
        if lite:
            return out
        st = eng.stats
        out.update(
            requests_completed=st.requests_completed,
            tokens_generated=st.tokens_generated,
            decode_tokens_per_s=round(st.decode_tokens_per_s, 2),
        )
        if depths is not None:
            # per-class outcome counters ride the full doc only
            out["preemptions"] = st.preemptions
            out["requests_shed"] = st.shed
        jnl = getattr(eng, "_journal", None)
        if jnl is not None:
            # write-ahead journal state (--journal): appended
            # bytes/records, fsync mode, whether the sink failed
            # open, and the last replay's outcome
            out["journal"] = jnl.state()
        if hasattr(eng, "current_config"):
            # the LIVE effective engine config (slots, decode_scan,
            # kv_pages, kv_dtype, mixed_batch, attn impl) so
            # operators can see what the autotuner chose; the epoch
            # pairs with per-request trace attribution
            out["engine_config"] = eng.current_config().to_dict()
        return out

    def autotune(self) -> dict:
        """GET /api/v1/autotune: mode, live config, window signals and
        the switch/decision history (cake_tpu/autotune)."""
        if self.engine is None or not hasattr(self.engine,
                                              "autotune_state"):
            return {"mode": "off",
                    "note": "engine-less serving has no autotuner"}
        return self.engine.autotune_state()

    def autotune_switch(self, body: dict) -> dict:
        """POST /api/v1/autotune {"config": {...}}: manual live
        switch. 400 on a malformed/invalid config or when --autotune
        is off; 409 (SwitchInFlightError, mapped by the handler) while
        another switch is in flight."""
        if self.engine is None or not hasattr(self.engine,
                                              "reconfigure"):
            raise ValueError("engine-less serving has no autotuner")
        if getattr(self.engine, "autotune_mode", "off") == "off":
            raise ValueError(
                "autotune is off; restart with --autotune manual (or "
                "auto) to enable live config switching")
        cfg = body.get("config")
        if not isinstance(cfg, dict):
            raise ValueError('body must be {"config": {...}} with the '
                             "switchable engine knobs")
        switched = self.engine.reconfigure(cfg, reason="manual")
        return {"switched": bool(switched),
                "config": self.engine.current_config().to_dict(),
                "epoch": self.engine.config_epoch}

    def drain(self, body: dict) -> dict:
        """POST /api/v1/drain {"timeout_s": N?}: graceful shutdown.
        Closes admissions immediately (new submits get 429 + the
        computed drain ETA as Retry-After), lets in-flight work finish
        for up to timeout_s (default 30), then snapshots whatever
        remains (--checkpoint) or leaves it journaled (--journal),
        stops the engine and shuts the HTTP server down cleanly.
        Responds immediately with the drain state; idempotent — a
        second POST reports progress without rearming."""
        if self.engine is None:
            raise ValueError("engine-less serving has no drain "
                             "(requests serialise on the generation "
                             "lock; stop the process instead)")
        timeout_s = body.get("timeout_s", 30.0)
        if (not isinstance(timeout_s, (int, float))
                or isinstance(timeout_s, bool) or timeout_s <= 0):
            raise ValueError("timeout_s must be a positive number")
        st = self.engine.begin_drain()
        with self._drain_lock:
            if self._drain_thread is None:
                self._drain_thread = threading.Thread(
                    target=self._drain_then_exit,
                    args=(float(timeout_s),), daemon=True,
                    name="cake-drain")
                self._drain_thread.start()
        return st

    def _drain_then_exit(self, timeout_s: float) -> None:
        """Drain-thread body: wait for the queue and the in-flight set
        to empty (bounded), then run the shared shutdown tail."""
        eng = self.engine
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            st = eng.drain_state()
            if st["pending_requests"] == 0 and st["queue_depth"] == 0:
                break
            time.sleep(0.05)
        else:
            log.warning("drain: timeout after %.1fs with %d request(s) "
                        "still in flight (snapshotted/journaled for "
                        "the next start where armed)", timeout_s,
                        eng.drain_state()["pending_requests"])
        shutdown = self._shutdown
        if shutdown is not None:
            shutdown()
        else:
            # standalone ApiServer (no start() wiring, e.g. tests):
            # stop the engine; post-drain submits then raise the typed
            # reset error instead of hanging
            eng.stop()

    def _engine_retry_after(self, priority=None) -> float:
        """Honest Retry-After for a transient engine reset: the shed
        controller's measured-service-rate estimate for the REQUEST'S
        priority class when shedding is on (the same computation
        behind the 429 path), else a 1s floor."""
        shed = getattr(self.engine, "_shed", None) \
            if self.engine is not None else None
        if shed is not None:
            try:
                return shed.estimate_retry_after(
                    priority or "standard", self.engine.queue_depth)
            except Exception:  # noqa: BLE001 — estimate, not contract
                log.debug("retry-after estimate failed", exc_info=True)
        return 1.0

    def fleet(self) -> dict:
        """GET /api/v1/fleet: per-host liveness, last-export age,
        applied control seq + lag, clock offset, device HBM gauges and
        health state — the coordinator composes its own entry (it runs
        the API; it is live by construction unless health failed) with
        the collector's remote views (obs/federation.py)."""
        local_name = getattr(self.collector, "local_host", None) \
            or "coordinator"
        failed = (self.health_state is not None
                  and self.health_state.failed)
        local: dict = {"role": "coordinator", "live": not failed}
        if failed:
            local["health"] = {"status": "failed",
                               "reason": self.health_state.reason}
        if self.engine is not None:
            local["active_requests"] = self.engine.active
            local["queue_depth"] = self.engine.queue_depth
        try:
            from cake_tpu.utils.profiling import device_memory_stats
            # SAME key names as the remote rows (_hbm_from_metrics —
            # derived from the cake_device_hbm_* gauge families), so a
            # dashboard reads hosts[*].hbm uniformly across roles
            keymap = (("bytes_in_use", "bytes_in_use"),
                      ("peak_bytes_in_use", "peak_bytes"),
                      ("bytes_limit", "bytes_limit"))
            local["hbm"] = {
                str(s["device"]): {out: s[src] for src, out in keymap
                                   if s.get(src) is not None}
                for s in device_memory_stats()
                if s.get("bytes_in_use") is not None}
        except Exception:  # noqa: BLE001 — fleet view is best-effort
            log.debug("local hbm stats unavailable", exc_info=True)
        out = {"local_host": local_name, "hosts": {local_name: local}}
        if self.collector is None:
            out["note"] = ("telemetry federation disabled "
                           "(single-host serving, or "
                           "--no-telemetry-export)")
            return out
        remote = self.collector.fleet()
        out["published_seq"] = remote.get("published_seq")
        out["stale_after_s"] = remote.get("stale_after_s")
        if out["published_seq"] is not None:
            # the coordinator publishes the op stream: by definition it
            # has applied everything it published
            local["applied_seq"] = out["published_seq"]
            local["lag_ops"] = 0
        out["hosts"].update(remote.get("hosts", {}))
        return out

    def cluster(self) -> dict:
        import jax
        from cake_tpu.parallel.distributed import cluster_info
        out = cluster_info()
        out["devices"] = [
            {"id": d.id, "platform": d.platform,
             "kind": d.device_kind, "process": d.process_index}
            for d in jax.devices()
        ]
        return out

    def metrics(self) -> str:
        """Prometheus text exposition of the serving metrics (the
        observability face of the reference's periodic worker-stat logs,
        worker.rs:254-283 — scrape-able instead of grep-able).

        Rendered from the obs.metrics registry: the request-latency
        histograms (TTFT / e2e / queue wait / prefill / inter-token)
        and per-route counters accumulate where the work happens; the
        engine's aggregate counters are synced here at scrape time (one
        scrape = one consistent snapshot of EngineStats)."""
        m = obs_metrics
        if self.health_state is None or not hasattr(
                self.health_state, "observe_metrics"):
            # per-device HBM gauges fresh at scrape instant (graceful
            # no-op on CPU backends); with a health state attached its
            # observe_metrics() below does this refresh instead —
            # calling both would pay Device.memory_stats() twice per
            # scrape on a multi-device host
            obs_steps.refresh_device_gauges()
        m.gauge("cake_requests_waiting",
                "Requests inside HTTP admission").set(self._waiting)
        m.gauge("cake_serving_healthy",
                "1 = serving, 0 = failed (parallel/health.py)").set(
            0 if (self.health_state is not None
                  and self.health_state.failed) else 1)
        if self.health_state is not None and hasattr(
                self.health_state, "observe_metrics"):
            # heartbeat staleness gauge + watchdog counters
            self.health_state.observe_metrics()
        if self.engine is not None:
            st = self.engine.stats
            for name, help_, val in (
                ("cake_engine_queue_depth",
                 "Admission queue depth", self.engine.queue_depth),
                ("cake_engine_active_requests",
                 "Requests holding a decode slot", self.engine.active),
                ("cake_engine_decode_slots",
                 "Configured decode slots", self.engine.max_slots),
                ("cake_engine_decode_tokens_per_second",
                 "Aggregate decode throughput",
                 round(st.decode_tokens_per_s, 2)),
                ("cake_engine_trace_active_requests",
                 "Requests with an open lifecycle trace",
                 self.engine.tracer.active_count),
            ):
                m.gauge(name, help_).set(val)
            for name, help_, val in (
                ("cake_engine_requests_completed_total",
                 "Requests retired by the engine",
                 st.requests_completed),
                ("cake_engine_tokens_generated_total",
                 "Tokens generated across all requests",
                 st.tokens_generated),
                ("cake_engine_decode_steps_total",
                 "Batched decode steps dispatched", st.steps),
                ("cake_engine_decode_seconds_total",
                 "Wall seconds inside decode dispatch",
                 round(st.decode_time_s, 4)),
                ("cake_engine_prefill_seconds_total",
                 "Wall seconds inside prefill dispatch",
                 round(st.prefill_time_s, 4)),
                ("cake_engine_prefix_hits_total",
                 "Prefills served from a registered prefix",
                 st.prefix_hits),
                ("cake_engine_errors_total",
                 "Engine iterations that failed and reset", st.errors),
            ):
                m.counter(name, help_).set_total(val)
            if getattr(self.engine, "_spec", False):
                m.counter("cake_engine_spec_proposed_total",
                          "Draft tokens proposed").set_total(
                    st.spec_proposed)
                m.counter("cake_engine_spec_accepted_total",
                          "Draft tokens accepted").set_total(
                    st.spec_accepted)
                m.gauge("cake_engine_spec_acceptance",
                        "Lifetime draft acceptance ratio").set(
                    round(st.spec_acceptance, 4))
            # scrape-fresh per-class queue depths through the engine's
            # one registration site (no-op without the SLO scheduler)
            self.engine._set_queue_gauges()
            obs_steps.refresh_page_gauges(self.engine)
            slo = getattr(self.engine, "slo", None)
            if slo is not None:
                # both attainment windows converge at scrape time even
                # between retirements (a quiet minute must roll the 1m
                # window forward, not freeze the last busy value)
                slo.refresh_gauges()
        if self.collector is not None:
            # per-host liveness/age gauges live in the LOCAL registry:
            # refresh them before rendering it
            try:
                self.collector.refresh_gauges()
            except Exception:  # noqa: BLE001 — a scrape must not fail
                log.debug("fleet gauge refresh failed", exc_info=True)
        text = m.REGISTRY.render()
        if self.collector is not None:
            # fleet federation: remote hosts' families appended with a
            # host label — families the coordinator also owns reuse its
            # HELP/TYPE block above, remote-only families bring their
            # own (one TYPE per family, the lint contract)
            try:
                text += self.collector.render_federated(
                    {f.name for f in m.REGISTRY.families()})
            except Exception:  # noqa: BLE001 — a scrape must not fail
                log.debug("federated render failed", exc_info=True)
        return text

    def requests(self, limit: Optional[int] = None,
                 rid: Optional[int] = None, cls: Optional[str] = None,
                 since: Optional[int] = None) -> dict:
        """Per-request lifecycle traces (GET /api/v1/requests): active
        requests first, then the finished ring, newest first —
        oldest-first with ?since= (cursor pagination pages forward).
        ?rid= / ?class= / ?since= filter (since is a rid cursor:
        strictly newer admissions only — poll with the previous
        response's `cursor`). The cursor is derived from the RETURNED
        records (a rid admitted mid-request, or truncated by ?limit=,
        stays strictly above it — never skipped)."""
        if self.engine is None:
            return {"requests": [], "note": "engine-less serving has "
                    "no request tracer"}
        recs = self.engine.tracer.dump(limit, rid=rid, cls=cls,
                                       since=since)
        if recs:
            cursor = max(r["rid"] for r in recs)
        else:
            cursor = since if since is not None else 0
        return {"requests": recs, "cursor": cursor}

    def request_timeline(self, rid: int) -> Optional[dict]:
        """Per-request explain (GET /api/v1/requests/{rid}/timeline):
        the request's trace spans, bus events and step records merged
        into one time-ordered view (obs/timeline.py). None -> 404."""
        if self.engine is None or not hasattr(self.engine,
                                              "request_timeline"):
            return None
        return self.engine.request_timeline(rid)

    def events(self, rid: Optional[int] = None,
               type: Optional[str] = None,
               since: Optional[int] = None,
               limit: Optional[int] = None,
               host: Optional[str] = None) -> dict:
        """Cross-subsystem event dump (GET /api/v1/events): ascending
        seq, ?rid= / ?type= / ?since= filtered (obs/events.py); the
        response `cursor` is the newest seq — pass it back as ?since=
        to read only what is new. ?host= selects a FLEET host's stream:
        the local host's name (or "local") serves this process's bus
        exactly as the unfiltered call does; a remote host name serves
        the collector-held view (timestamps clock-offset-corrected,
        seqs/cursors are that host's own). Unknown hosts are a 400 via
        ValueError — the caller named a host, silently dumping
        everything would be the opposite of the ask."""
        local_name = getattr(self.collector, "local_host", None)
        if host is not None and host not in ("local", local_name):
            if self.collector is None:
                raise ValueError(
                    f"?host={host!r}: telemetry federation is "
                    "disabled (no collector); only local events exist")
            known = self.collector.hosts()
            if host not in known:
                raise ValueError(
                    f"unknown host {host!r} (local: "
                    f"{local_name or 'local'}; exporting: "
                    f"{', '.join(known) or 'none yet'})")
            # the collector owns the cursor-pagination contract
            # (events_page mirrors EventBus.snapshot), so local and
            # remote streams page identically
            evs, cursor = self.collector.events_page(
                host, rid=rid, type=type, since=since, limit=limit)
            return {"events": evs, "host": host, "cursor": cursor}
        bus = getattr(self.engine, "events", None) \
            if self.engine is not None else None
        if bus is None:
            return {"events": [], "cursor": 0,
                    "note": "event bus disabled (--event-ring 0) or "
                            "engine-less serving"}
        evs, cursor = bus.snapshot(rid=rid, type=type, since=since,
                                   limit=limit)
        out = {"events": evs, "cursor": cursor}
        if host is not None:
            out["host"] = local_name or "local"
        return out

    def anomalies(self, limit: Optional[int] = None) -> dict:
        """Online regression-sentinel dump (GET /api/v1/anomalies):
        active anomalies, the recent-firing ring (?limit=), every
        detector's threshold/state (obs/sentinel.py; armed by
        --sentinel), and — with --sentinel-act — the closed-loop
        action history (obs/actions.py)."""
        sen = (self.engine.sentinel if self.engine is not None
               else None)
        if sen is None:
            return {"active": [], "anomalies": [],
                    "note": "sentinel disabled (restart with "
                            "--sentinel) or engine-less serving"}
        out = sen.state(limit=limit)
        plane = getattr(self.engine, "_actions", None)
        if plane is not None:
            out["actions"] = plane.history(limit)
            out["action_rate_per_min"] = plane.max_per_min
        return out

    def steps(self, limit: Optional[int] = None) -> dict:
        """Step flight-recorder dump (GET /api/v1/steps): newest step
        records first plus the aggregate summary (per-kind counts,
        compile counts, decode-side MFU / HBM utilization)."""
        if self.engine is None or not hasattr(self.engine, "flight"):
            return {"steps": [], "summary": {},
                    "note": "engine-less serving has no step recorder"}
        return {"steps": self.engine.flight.dump(limit),
                "summary": self.engine.flight.summary()}

    def profile(self, body: dict) -> dict:
        """On-demand profiler capture (POST /api/v1/profile
        {"seconds": N}): grab a jax.profiler Perfetto trace of the next
        N seconds of live execution and return the artifact paths.
        Single-flight: a concurrent capture raises ProfileBusyError
        (HTTP 409). The capture directory comes from --profile-dir
        (never the request body — clients must not pick server paths)."""
        if not isinstance(body, dict):
            # valid JSON but not an object (e.g. `[2]`): client error,
            # not a 500 + exception log
            raise ValueError("body must be a JSON object")
        seconds = body.get("seconds", 2.0)
        if not isinstance(seconds, (int, float)) or isinstance(
                seconds, bool):
            raise ValueError("seconds must be a number")
        return obs_steps.PROFILER.capture(seconds, self._profile_dir)

    # -- admission -----------------------------------------------------------

    def _admission(self):
        server = self

        class _Adm:
            def __enter__(self):
                with server._waiting_lock:
                    if server._waiting >= MAX_WAITING:
                        raise QueueFull()
                    server._waiting += 1

            def __exit__(self, *exc):
                with server._waiting_lock:
                    server._waiting -= 1
        return _Adm()


# chat() return sentinel: the streaming client went away (handled; the
# HTTP layer must not touch the dead socket again)
DISCONNECTED = object()


def _accepts_kwarg(fn, name: str) -> bool:
    """Whether calling fn(..., name=...) is safe: the callback
    evolution contract for chat()'s send_chunk (event_id=) and
    on_start (rid=) — older zero/one-arg callables (embedders, tests)
    keep working, newer ones opt in by naming the kwarg (or taking
    **kwargs)."""
    import inspect
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    return (name in params
            or any(p.kind == inspect.Parameter.VAR_KEYWORD
                   for p in params.values()))


class QueueFull(Exception):
    """Admission rejected: queue full, load-shed (shed=True), or the
    server is draining (draining=True — POST /api/v1/drain or SIGTERM
    in flight). retry_after seconds ride the HTTP 429 Retry-After
    header — computed from the measured service rate when shedding is
    on (sched/shed.py), from the drain ETA when draining, a 1s floor
    otherwise."""

    def __init__(self, retry_after: float = 1.0, shed: bool = False,
                 draining: bool = False):
        super().__init__("server draining" if draining
                         else "request shed" if shed else "queue full")
        self.retry_after = retry_after
        self.shed = shed
        self.draining = draining


def make_handler(api: ApiServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log.debug("http: " + fmt, *args)

        def _json(self, code: int, obj: dict):
            data = json.dumps(obj).encode()
            self.send_response(code)
            if code >= 400 and getattr(self, "_trace", None):
                # echo the request's trace id on error responses: the
                # router relays non-200s verbatim, so a refused/failed
                # request still hands its caller the federated-
                # timeline key
                self.send_header("x-cake-trace", self._trace)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            api._count(self.path, code)

        def _retry_json(self, code: int, retry_after_s: float,
                        obj: dict):
            """_json plus a Retry-After header (ceil'd to whole
            seconds, floor 1) — the shared shape of the 429 overload
            and 503 engine-reset responses; retry_after_s also rides
            the body as retry_after_s."""
            retry = max(1, int(-(-retry_after_s // 1)))
            data = json.dumps({**obj, "retry_after_s": retry}).encode()
            self.send_response(code)
            self.send_header("Retry-After", str(retry))
            # attribute the backpressure to THIS replica: the router
            # relays the header verbatim, so clients and router logs
            # can tell which backend computed the Retry-After
            self.send_header("x-cake-replica", str(api.replica_id))
            if getattr(self, "_trace", None):
                self.send_header("x-cake-trace", self._trace)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            api._count(self.path, code)

        def _query(self) -> dict:
            """First value of each query param (the filter endpoints'
            input; repeated params keep the first — filters are
            scalar)."""
            if "?" not in self.path:
                return {}
            from urllib.parse import parse_qs
            return {k: v[0] for k, v in
                    parse_qs(self.path.split("?", 1)[1]).items() if v}

        @staticmethod
        def _int_arg(q: dict, key: str):
            """Integer query param or None; a malformed value is a 400
            (silently ignoring ?rid=abc would dump everything — the
            opposite of what the caller asked)."""
            v = q.get(key)
            if v is None:
                return None
            try:
                return int(v)
            except ValueError:
                raise ValueError(f"?{key}= must be an integer, got "
                                 f"{v!r}")

        def _read_body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            if n == 0:
                return {}
            try:
                return json.loads(self.rfile.read(n))
            except json.JSONDecodeError:
                raise ValueError("invalid JSON body")

        def do_GET(self):
            # re-stash per request: on a keep-alive connection a stale
            # value from an earlier POST would mis-attribute this
            # request's error responses to that POST's trace
            self._trace = self.headers.get("x-cake-trace")
            route = self.path.split("?", 1)[0]
            if route == "/api/v1/health":
                # ?lite=1: the router's cheap poll variant (a subtree
                # of the full document; any other value means full)
                lite = self._query().get("lite") == "1"
                return self._json(200, api.health(lite=lite))
            if self.path == "/api/v1/cluster":
                return self._json(200, api.cluster())
            if route == "/api/v1/requests":
                q = self._query()
                try:
                    cls = q.get("class")
                    if cls is not None:
                        from cake_tpu.sched.classes import (
                            validate_priority,
                        )
                        validate_priority(cls)
                    return self._json(200, api.requests(
                        limit=self._int_arg(q, "limit"),
                        rid=self._int_arg(q, "rid"), cls=cls,
                        since=self._int_arg(q, "since")))
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
            m = _TIMELINE_RE.match(route)
            if m:
                tl = api.request_timeline(int(m.group(1)))
                if tl is None:
                    return self._json(404, {
                        "error": f"unknown rid {m.group(1)} (not "
                                 "admitted, or fell out of the "
                                 "finished-trace ring)"})
                return self._json(200, tl)
            if route == "/api/v1/events":
                q = self._query()
                try:
                    t = q.get("type")
                    if t is not None:
                        from cake_tpu.obs.events import EVENT_TYPES
                        if t not in EVENT_TYPES:
                            raise ValueError(
                                f"unknown event type {t!r} (choose "
                                f"one of {', '.join(EVENT_TYPES)})")
                    return self._json(200, api.events(
                        rid=self._int_arg(q, "rid"), type=t,
                        since=self._int_arg(q, "since"),
                        limit=self._int_arg(q, "limit"),
                        host=q.get("host")))
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
            if route == "/api/v1/fleet":
                return self._json(200, api.fleet())
            if route == "/api/v1/steps":
                try:
                    return self._json(200, api.steps(
                        self._int_arg(self._query(), "limit")))
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
            if route == "/api/v1/anomalies":
                try:
                    return self._json(200, api.anomalies(
                        self._int_arg(self._query(), "limit")))
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
            if self.path == "/api/v1/autotune":
                return self._json(200, api.autotune())
            if self.path in ("/v1/models", "/api/v1/models"):
                # OpenAI client compatibility: SDKs list models on init
                return self._json(200, {
                    "object": "list",
                    "data": [{"id": api.model_name, "object": "model",
                              "created": api.started_at,
                              "owned_by": "cake-tpu"}],
                })
            if self.path in ("/metrics", "/api/v1/metrics"):
                data = api.metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                api._count(self.path, 200)
                return
            self._json(404, {"error": "not found"})  # api/mod.rs:19-21

        def do_POST(self):
            # stashed for the error-path x-cake-trace echo (_json /
            # _retry_json): SSE streams echo it via on_start instead
            self._trace = self.headers.get("x-cake-trace")
            try:
                body = self._read_body()
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            # profiling must work on a FAILED server (a wedged mesh is
            # exactly when an operator wants a live trace), so it
            # dispatches before the health gate below
            if self.path == "/api/v1/profile":
                try:
                    return self._json(200, api.profile(body))
                except obs_steps.ProfileBusyError as e:
                    return self._json(409, {"error": str(e)})
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    log.exception("profile capture failed")
                    return self._json(
                        500, {"error": f"{type(e).__name__}: {e}"})
            if self.path == "/api/v1/drain":
                # dispatches before the health gate below: draining a
                # FAILED server is exactly how an operator evacuates it
                try:
                    return self._json(200, api.drain(body))
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    log.exception("drain failed")
                    return self._json(
                        500, {"error": f"{type(e).__name__}: {e}"})
            # after the body read: responding early would leave unread
            # body bytes desyncing this keep-alive connection
            if api.health_state is not None and api.health_state.failed:
                # fail fast instead of queueing work onto a dead mesh
                return self._json(503, {
                    "error": f"serving failed: {api.health_state.reason}"})
            try:
                if self.path in ("/api/v1/chat/completions",
                                 "/v1/chat/completions"):
                    # the /v1 alias serves OpenAI SDKs pointed at
                    # base_url=.../v1 (they discover via /v1/models)
                    return self._chat(body)
                if self.path == "/api/v1/autotune":
                    from cake_tpu.serve.errors import SwitchInFlightError
                    try:
                        return self._json(200, api.autotune_switch(body))
                    except SwitchInFlightError as e:
                        # one switch at a time: folding every stream is
                        # expensive and a queued second switch would
                        # thrash — the client retries after this one
                        return self._json(409, {"error": str(e)})
                    # ValueError (bad config / autotune off) falls to
                    # the generic 400 below
                if self.path == "/api/v1/image":
                    return self._json(200, api.image(body))
                return self._json(404, {"error": "not found"})
            except ValueError as e:
                # invalid option combinations (e.g. logprobs on the
                # engine-less path) are client errors, not server faults
                if getattr(self, "_stream_started", False):
                    return
                return self._json(400, {"error": str(e)})
            except QueueFull as e:
                if getattr(self, "_stream_started", False):
                    return  # headers already gone; just drop the connection
                # 429 + an HONEST Retry-After: computed seconds until
                # the backlog drains inside the class SLO at the
                # measured service rate (sched/shed.py) or the drain
                # completes (engine.drain_state), not a hardcoded
                # constant — for shed, queue-full and draining alike
                self._retry_json(429, e.retry_after, {
                    "error": ("server draining: admissions are closed"
                              if getattr(e, "draining", False)
                              else "request shed: server saturated for "
                              "this priority class" if e.shed
                              else "queue full"),
                })
            except EngineRequestError as e:
                # typed engine failures (serve/errors.py): a RETRYABLE
                # one (transient reset, storm-breaker stop) is 503 +
                # an honest computed Retry-After — the request itself
                # was fine; a non-retryable one (poison request) is a
                # terminal 500 the client must not blindly resubmit
                log.warning("engine failed request: %s", e)
                if getattr(self, "_stream_started", False):
                    return  # the stream already carried its error event
                if not e.retryable:
                    return self._json(500, {
                        "error": str(e), "retryable": False})
                # body["priority"] holds the merged body/header class
                # (set by _chat before submit), so the estimate is for
                # the failing request's own lane — matching the 429
                # path's per-class computation
                self._retry_json(
                    503,
                    api._engine_retry_after(body.get("priority")),
                    {"error": str(e), "retryable": True})
            except Exception as e:  # noqa: BLE001
                log.exception("request failed")
                if getattr(self, "_stream_started", False):
                    return
                self._json(500, {"error": f"{type(e).__name__}: {e}"})

        def _chat(self, body: dict):
            # x-cake-priority header names the SLO class for clients
            # that cannot edit the body (gateways, sidecars); an
            # explicit body "priority" wins — a JSON null counts as
            # unset (SDKs serialize optional fields as null), so the
            # header still applies then. Unknown values 400 via
            # parse_chat_request's validation.
            hdr = self.headers.get("x-cake-priority")
            if hdr is not None and body.get("priority") is None:
                body["priority"] = hdr
            # durable serving (serve/journal.py): a retried submit
            # carrying the same x-cake-idempotency-key attaches to the
            # existing stream instead of double-admitting; on a
            # streaming reconnect, Last-Event-ID (the standard SSE
            # resume header — the absolute token id of the last event
            # the client saw) replays exactly the missing suffix
            idem_key = self.headers.get("x-cake-idempotency-key")
            last_id = self.headers.get("Last-Event-ID")
            # distributed tracing (x-cake-trace, minted by the
            # front-door router or a client): threaded to the engine
            # tracer + event bus at admission, echoed on the SSE
            # response headers (with the engine rid) and on error
            # responses — the federated timeline's correlation key
            trace = self.headers.get("x-cake-trace")
            if last_id is not None:
                try:
                    last_id = int(last_id)
                except ValueError:
                    raise ValueError(
                        f"Last-Event-ID must be an integer event id, "
                        f"got {last_id!r}")
                if idem_key is None:
                    raise ValueError(
                        "Last-Event-ID resume requires "
                        "x-cake-idempotency-key (the key names the "
                        "stream across reconnects and restarts)")
            if not body.get("stream"):
                return self._json(200, api.chat(
                    body, idempotency_key=idem_key, trace_id=trace))
            self._stream_started = False

            def on_start(rid=None):
                # only once admission + the generation lock are held
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                # attribution before any tokens: which replica serves
                # this stream, under which trace, as which engine rid
                # (the router relays these to its client and joins the
                # trace to this replica's timeline through the rid)
                self.send_header("x-cake-replica", str(api.replica_id))
                if trace is not None:
                    self.send_header("x-cake-trace", trace)
                if rid is not None:
                    self.send_header("x-cake-rid", str(rid))
                self.end_headers()
                self._stream_started = True

            def send_chunk(obj: dict, event_id=None):
                # the `id:` field makes the stream resumable: it is the
                # absolute token position this event covers up to, and
                # a reconnect echoes it back as Last-Event-ID
                head = (f"id: {int(event_id)}\n"
                        if event_id is not None else "")
                payload = f"{head}data: {json.dumps(obj)}\n\n".encode()
                self.wfile.write(hex(len(payload))[2:].encode() + b"\r\n")
                self.wfile.write(payload + b"\r\n")
                self.wfile.flush()

            outcome = api.chat(body, send_chunk=send_chunk,
                               on_start=on_start,
                               idempotency_key=idem_key,
                               last_event_id=last_id,
                               trace_id=trace)
            if outcome is DISCONNECTED:
                # handled disconnect: the socket is dead, writing the
                # trailer would only manufacture an error traceback
                api._count(self.path, 200)
                return
            done = b"data: [DONE]\n\n"
            self.wfile.write(hex(len(done))[2:].encode() + b"\r\n")
            self.wfile.write(done + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")
            api._count(self.path, 200)

    return Handler


def start(master, address: str = "127.0.0.1:10128",
          model_name: str = "cake-tpu", block: bool = True, engine=None,
          checkpoint_path: str | None = None, health=None,
          collector=None, announce: str | None = None,
          announce_interval_s: float = 2.0,
          announce_token: str | None = None):
    """Bind and serve (reference api/mod.rs:23-48). When the master holds a
    text model, a continuous-batching engine is built automatically so
    concurrent chat requests share the decode loop.

    checkpoint_path: restore any in-flight requests recorded by a previous
    shutdown, and snapshot unfinished requests on SIGTERM/serve_forever
    exit (serve/checkpoint.py).

    announce: a front-door router's announce listener ("host:port",
    --router-announce on the replica role) — this replica self-registers
    there and ships lite-health-superset telemetry frames every
    announce_interval_s (router/discovery.ReplicaAnnouncer); shutdown
    ships an explicit departure notice FIRST so the router
    drains-then-forgets instead of inferring death from silence."""
    host, port = address.rsplit(":", 1)
    if engine is None and master.llm is not None:
        engine = master.make_engine()
    if engine is None and master.llm is not None:
        # engine-less locked-path serving: unreachable for the built-in
        # compositions as of round-5 (every sp mode has an engine
        # contract), kept for custom forward adapters that provide no
        # engine_pieces. These flags gate on
        # the engine and silently doing nothing would surprise operators
        if checkpoint_path:
            log.warning("--checkpoint does not apply to engine-less "
                        "(locked-path) serving; no snapshots will be "
                        "taken")
        log.info("engine-less serving: stall watchdog and /metrics "
                 "engine counters are unavailable")
    if health is None and engine is not None:
        # always-on progress watchdog; multi-host callers pass a
        # ServingHealth that additionally heartbeats the followers
        from cake_tpu.parallel.health import ServingHealth
        health = ServingHealth(engine, stall_after_s=getattr(
            master.args, "stall_timeout", 600.0))
    api = ApiServer(master, model_name, engine=engine, health=health,
                    collector=collector, replica_id=address)
    httpd = ThreadingHTTPServer((host, int(port)), make_handler(api))
    log.info("REST API listening on %s", address)

    announcer = None
    if announce is not None:
        from cake_tpu.router.discovery import ReplicaAnnouncer
        # the announced identity doubles as the router's proxy target,
        # so it must be dialable FROM the router: the bound port (a
        # port-0 bind resolves here), and a concrete host when we
        # bound a wildcard
        ahost = host if host not in ("", "0.0.0.0", "::") else "127.0.0.1"
        announcer = ReplicaAnnouncer(
            announce, f"{ahost}:{httpd.server_address[1]}",
            token=announce_token, interval_s=announce_interval_s,
            health=lambda: api.health(lite=True), engine=engine)
        log.info("announcing to router at %s as %s", announce,
                 announcer.replica)

    journal_armed = (engine is not None
                     and getattr(engine, "_journal", None) is not None)
    if engine is not None and (checkpoint_path or journal_armed):
        import os

        from cake_tpu.serve import checkpoint as ckpt

        # arm the pre-fail snapshot: a serving failure (heartbeat loss,
        # engine error) checkpoints in-flight requests BEFORE failing
        # them (engine._fail_all), so a cluster restart resumes them.
        # The weight digest is computed NOW, while the mesh is healthy —
        # at fail time the device stream may be wedged (and the
        # journal's generation header wants it warm for the same
        # reason)
        if checkpoint_path:
            engine.snapshot_path = checkpoint_path
        ckpt.warm_fingerprint(engine)

        if journal_armed:
            from cake_tpu.serve import journal as jr
            try:
                # cold-restart recovery: checkpoint base + journal
                # replay, resubmitted through the fold path — every
                # non-retired stream a kill -9 interrupted completes
                # (greedy: token-identical at f32 KV)
                handles, _ = jr.recover(
                    engine, checkpoint_path=checkpoint_path,
                    strict=True)
                if handles:
                    log.info("journal replay resubmitted %d in-flight "
                             "request(s)", len(handles))
            except Exception as e:  # noqa: BLE001
                # a fingerprint mismatch / unreadable state must not
                # crash-loop startup; sideline the evidence so the
                # next save starts clean
                jpath = engine._journal.path
                for p in (checkpoint_path, jpath,
                          jpath + ".replaying"):
                    if p and os.path.exists(p):
                        try:
                            os.replace(p, p + ".invalid")
                        except OSError:
                            pass
                log.warning("journal/checkpoint replay failed (%s); "
                            "sidelined to *.invalid and starting with "
                            "an empty engine", e)
        elif checkpoint_path and os.path.exists(checkpoint_path):
            try:
                # strict: a fingerprint mismatch (e.g. different weights
                # with identical shapes) must NOT silently replay tokens —
                # the except below sidelines the snapshot instead
                handles, _ = ckpt.restore(engine, checkpoint_path,
                                          strict=True)
                log.info("restored %d in-flight request(s) from %s",
                         len(handles), checkpoint_path)
            except Exception as e:  # noqa: BLE001
                # an unreadable/old-version/incompatible snapshot must not
                # crash-loop server startup; sideline it so the evidence
                # survives and the next save starts clean
                bad = f"{checkpoint_path}.invalid"
                try:
                    os.replace(checkpoint_path, bad)
                except OSError:
                    bad = checkpoint_path
                log.warning("checkpoint restore failed (%s); moved to %s "
                            "and starting with an empty engine", e, bad)

    if engine is not None:
        done = threading.Event()

        def save_and_exit(*_sig):
            if done.is_set():
                return
            done.set()
            # order matters: the router hears the departure notice
            # FIRST (it stops routing NEW work here while our
            # in-flight streams finish — drain-then-forget), then
            # close admissions (new submits 429 with the drain ETA
            # instead of racing the stop), then stop the engine
            # (post-stop submits raise the typed reset error), then
            # snapshot, then tear down HTTP. shutdown() must run on a
            # helper thread — called from the serve_forever thread
            # (the block=True signal path) it deadlocks.
            if announcer is not None:
                announcer.depart()
            try:
                engine.begin_drain()
            except Exception:  # noqa: BLE001
                pass
            engine.stop()
            pm = getattr(engine, "_postmortem", None)
            if pm is not None:
                # black-box bundle on the termination path too: the
                # engine thread is stopped, so every ring is final
                pm.dump("sigterm", engine=engine, force=True)
            if checkpoint_path:
                # keep-or-save decision lives in the engine
                # (shutdown_save), under the same lock as the pre-fail
                # writer: a pre-fail snapshot written by THIS process
                # is authoritative and kept; a checkpoint consumed by
                # this process's restore is overwritten so completed
                # resumes don't replay forever. (With --journal, the
                # write also truncates the journal — the handshake
                # keeping the two restart sources disjoint.)
                engine.shutdown_save(checkpoint_path)
            elif not journal_armed:
                # nothing will resume these after restart: release any
                # still-open waiters with the typed reset error
                # instead of letting them hang until process death
                from cake_tpu.serve.errors import EngineResetError
                engine._fail_all(EngineResetError(
                    "server stopped while this request was in flight"))
            if announcer is not None:
                # terminal frame: the departure notice again, now with
                # the drained (zero-load) health doc — the router's
                # forget condition
                announcer.close()
            threading.Thread(target=httpd.shutdown, daemon=True).start()

        api._shutdown = save_and_exit
        try:
            import signal

            prev_handler = signal.getsignal(signal.SIGTERM)

            def on_sigterm(signum, frame):
                save_and_exit()
                # chain whatever handler was installed before us (an
                # application-level cleanup, jax.distributed teardown, …)
                # instead of silently clobbering it
                if callable(prev_handler):
                    prev_handler(signum, frame)
                elif prev_handler == signal.SIG_DFL:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    signal.raise_signal(signal.SIGTERM)

            signal.signal(signal.SIGTERM, on_sigterm)
        except ValueError:
            pass  # not the main thread; caller owns signal handling
    else:
        save_and_exit = None

    def serve():
        try:
            httpd.serve_forever()
        finally:
            # snapshot on EVERY exit path (SIGINT, external shutdown()),
            # not just SIGTERM
            if save_and_exit is not None:
                save_and_exit()
            elif announcer is not None:
                # engine-less serving: no save_and_exit path to ship
                # the departure notice — do it here
                announcer.close()
            if health is not None:
                health.close()

    if block:
        serve()
    else:
        t = threading.Thread(target=serve, daemon=True)
        t.start()
    return httpd
