"""OpenAI-compatible REST serving.

Capability parity with the reference's actix-web API (cake-core/src/cake/api/):
  POST /api/v1/chat/completions  (api/mod.rs:38, text.rs:54-96)
  POST /api/v1/image             (api/mod.rs:39, image.rs:25-68)
plus upgrades called out in SURVEY.md §7.4: SSE streaming (the reference
buffers the whole completion), a health/cluster introspection endpoint
(WorkerInfo equivalent), and a request queue instead of silently holding a
global write lock.
"""

from cake_tpu.api.server import ApiServer, start  # noqa: F401
