"""Disaggregated prefill/decode: ship KV pages over the wire.

Prefill bursts steal MXU time and pool pages from resident decode
streams; the mixed-step path only papers over the interference on one
chip. This module is the data plane that splits the two phases onto
separate engines: a token-gated, length-prefixed page channel (the
utils/wire.py framing both coordination planes already speak) that
ships raw pool slices + scale sidecars dtype-blind through the
host_tier.py fetch/install seam — f32, int8 and int4 pages round-trip
bit-identical, and a quantized shipment moves ~4x (int8) / ~8x (int4)
fewer bytes than f32 for the same prefix.

Roles (``--disagg {prefill,decode}`` + ``--disagg-peer host:port``):

  * the DECODE engine is the front door: an admitted request is held
    out of the scheduler while ``DisaggDecodePlane`` forwards its
    prompt to the prefill peer; the shipped pages install through the
    refcounted allocator and the stream adopts at the shipped frontier
    (engine._adopt_install — the _restore_victim shape), serving SSE
    from the first decoded token;
  * the PREFILL engine (``DisaggPrefillPlane``) admits the forwarded
    prompt as a stock max_new_tokens=1 request — chunked prefill into
    pool pages, first token sampled — then fetches the written pages
    at retirement (engine._capture_shipment) and ships them with a
    journal-style handoff record before the allocator frees them.

Wire shape: every frame is one length-prefixed message whose payload
is ``!I`` header-length + JSON header + raw binary tail. A shipment is
``ship_begin`` (geometry, dtype, array specs, handoff record), N
``ship_chunk`` frames — chunked along the layer axis at ~1 MiB so a
1k-token prefix is a handful of frames, each carrying (config epoch,
layer range, page ids, dtype, crc32) — and ``ship_end``;
``ship_fail`` aborts. The receiver resumes partial frames across recv
timeouts (the ControlClient._rbuf discipline, PR 8) and refuses
checksum or config-epoch mismatches loudly.

Failure is first-class: fault sites ``kv.ship``/``kv.adopt``
(faults/plan.py) inject at the capture/install seams, and every
channel failure — peer down, timeout, corrupt or stale shipment —
degrades to whole-prompt prefill on the decode host (the
_effective_hit rule) instead of wedging the stream.

Metrics (obs/metrics.py registry; README metrics table):
  cake_kv_ship_total{dir}          counter  shipments sent | received
  cake_kv_ship_bytes_total{dtype}  counter  page bytes over the wire
  cake_kv_ship_seconds             histogram wall seconds per shipment
  cake_kv_adopt_total{outcome}     counter  adoption outcomes
"""

from __future__ import annotations

import hmac
import json
import logging
import queue
import socket
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.utils.wire import LEN, recv_bounded_msg, send_msg

log = logging.getLogger(__name__)

_SHIP_TOTAL = obs_metrics.counter(
    "cake_kv_ship_total",
    "KV page shipments over the disaggregated transfer channel, by "
    "direction (out = prefill host sent, in = decode host received "
    "intact)",
    labelnames=("dir",))
_SHIP_BYTES = obs_metrics.counter(
    "cake_kv_ship_bytes_total",
    "KV page payload bytes sent over the transfer channel, by pool "
    "storage dtype (int8/int4 shipments move the quantized pages + "
    "scale sidecars — ~4x/~8x fewer bytes than f32)",
    labelnames=("dtype",))
_SHIP_SECONDS = obs_metrics.histogram(
    "cake_kv_ship_seconds",
    "Wall seconds to encode and send one complete KV page shipment "
    "(prefill-host writer thread, ship_begin through ship_end)")
_ADOPT_TOTAL = obs_metrics.counter(
    "cake_kv_adopt_total",
    "Shipped-prefill adoption outcomes on the decode host (adopted = "
    "pages installed and the stream resumed at the shipped frontier; "
    "degraded/timeout/checksum/epoch/geometry/fault/error = the "
    "documented fall-back to whole-prompt local prefill)",
    labelnames=("outcome",))


def note_adopt(outcome: str) -> None:
    """One adoption outcome (engine._adopt_install / the decode plane
    degradation paths) — the single writer for cake_kv_adopt_total."""
    _ADOPT_TOTAL.labels(outcome=outcome).inc()


# frame geometry: chunk blobs target ~1 MiB so a long prefix streams
# as a handful of frames (never one giant allocation at the receiver);
# the recv cap bounds what a corrupt/hostile length prefix can make us
# buffer. Hello frames are tiny and separately capped.
CHUNK_BYTES = 1 << 20
MAX_FRAME_BYTES = 64 << 20
HELLO_BYTES = 256
HELLO_TIMEOUT_S = 5.0

_HDR = struct.Struct("!I")


def encode_frame(header: dict, blob: bytes = b"") -> bytes:
    """One channel frame payload: header-length + JSON header + raw
    binary tail (empty for control messages)."""
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return _HDR.pack(len(hdr)) + hdr + blob


def decode_frame(payload: bytes) -> Tuple[dict, bytes]:
    """Inverse of encode_frame; raises ValueError on anything
    malformed (a corrupt frame must refuse loudly, never mis-slice)."""
    if len(payload) < _HDR.size:
        raise ValueError("transfer frame shorter than its header length")
    (n,) = _HDR.unpack(payload[:_HDR.size])
    if not 0 < n <= len(payload) - _HDR.size:
        raise ValueError(f"transfer frame header length {n} out of "
                         f"bounds for a {len(payload)}-byte payload")
    try:
        header = json.loads(payload[_HDR.size:_HDR.size + n])
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"transfer frame header is not JSON: {e}")
    if not isinstance(header, dict) or "t" not in header:
        raise ValueError("transfer frame header missing its type tag")
    return header, payload[_HDR.size + n:]


@dataclass
class Shipment:
    """One prefilled prompt's KV pages in flight: the raw pool slices
    (host_tier.fetch_pages layout — quantized pools ship
    (k_q, k_scale, v_q, v_scale), plain pools (k, v)) plus everything
    the decode host needs to adopt the stream at the shipped frontier.
    ``epoch`` is the DECODE host's config epoch, echoed from its
    prefill request — a reconfigure while the shipment was in flight
    makes it stale and adoption refuses it."""

    epoch: int
    dtype: str            # pool_dtype_name: "int8" | "int4" | array dtype
    page_size: int
    n_tokens: int         # prompt tokens whose KV the pages hold
    n_written: int        # pages with content == ceil(n_tokens/page_size)
    first_tok: int        # sampled on the prefill host; emitted verbatim
    pages: List[int]      # prefill-host page ids (diagnostic provenance)
    arrays: Tuple[np.ndarray, ...]
    handoff: dict = field(default_factory=dict)

    @property
    def payload_bytes(self) -> int:
        return sum(int(a.nbytes) for a in self.arrays)


def validate_shipment_header(h: dict) -> None:
    """Refuse malformed ship_begin metadata loudly (ValueError) before
    allocating receive buffers: geometry that cannot describe a real
    pool slice — an int4 pool with an odd page size (nibble packing
    holds two tokens per byte), a written-page count that disagrees
    with the token count, a page axis that disagrees with both."""
    page_size = int(h["page_size"])
    n_tokens = int(h["n_tokens"])
    n_written = int(h["n_written"])
    if page_size < 1 or n_tokens < 1 or n_written < 1:
        raise ValueError(
            f"shipment geometry must be positive (page_size={page_size}"
            f", n_tokens={n_tokens}, n_written={n_written})")
    if h["dtype"] == "int4" and page_size % 2:
        raise ValueError(
            f"int4 pages nibble-pack two tokens per byte; odd "
            f"page_size {page_size} is not valid int4 metadata")
    if n_written != -(-n_tokens // page_size):
        raise ValueError(
            f"n_written {n_written} != ceil({n_tokens}/{page_size}) — "
            "the shipment does not cover exactly the prompt's pages")
    specs = h["arrays"]
    if not specs:
        raise ValueError("shipment carries no arrays")
    L = int(specs[0]["shape"][0])
    for spec in specs:
        shape = [int(d) for d in spec["shape"]]
        if len(shape) < 2 or shape[0] != L or shape[1] != n_written:
            raise ValueError(
                f"array spec {shape} does not match the shipment "
                f"geometry [L={L}, n_pages={n_written}, ...]")
        np.dtype(spec["dtype"])   # unknown dtype names refuse here
    if not isinstance(h.get("pages"), list) \
            or len(h["pages"]) != n_written:
        raise ValueError("shipment page-id list disagrees with "
                         "n_written")


def shipment_frames(ship: Shipment, tag: int):
    """Yield the encoded frames for one shipment: ship_begin, the
    layer-range chunks, ship_end. Chunks slice every array along the
    layer axis together so the receiver scatters each chunk straight
    into its preallocated buffers."""
    arrays = [np.ascontiguousarray(a) for a in ship.arrays]
    L = int(arrays[0].shape[0])
    per_layer = sum(a.nbytes // L for a in arrays) or 1
    step = max(1, CHUNK_BYTES // per_layer)
    ranges = [(lo, min(lo + step, L)) for lo in range(0, L, step)]
    yield encode_frame({
        "t": "ship_begin", "tag": tag, "epoch": ship.epoch,
        "dtype": ship.dtype, "page_size": ship.page_size,
        "n_tokens": ship.n_tokens, "n_written": ship.n_written,
        "first_tok": ship.first_tok, "pages": list(ship.pages),
        "arrays": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                   for a in arrays],
        "n_chunks": len(ranges), "handoff": dict(ship.handoff),
    })
    for seq, (lo, hi) in enumerate(ranges):
        blob = b"".join(a[lo:hi].tobytes() for a in arrays)
        yield encode_frame({
            "t": "ship_chunk", "tag": tag, "seq": seq,
            "epoch": ship.epoch, "dtype": ship.dtype,
            "layer_lo": lo, "layer_hi": hi, "pages": list(ship.pages),
            "crc": zlib.crc32(blob) & 0xFFFFFFFF,
        }, blob)
    yield encode_frame({"t": "ship_end", "tag": tag,
                        "n_chunks": len(ranges)})


class ShipmentAssembler:
    """Receive-side reassembly of one shipment: preallocates the
    arrays from the ship_begin specs, scatters each chunk's layer
    range, and refuses — ValueError, the caller degrades — checksum
    mismatches, config-epoch drift between frames, out-of-order or
    mis-sized chunks, and invalid (e.g. odd-page int4) metadata."""

    def __init__(self, begin: dict):
        validate_shipment_header(begin)
        self.begin = begin
        self.epoch = int(begin["epoch"])
        self.n_chunks = int(begin["n_chunks"])
        self.next_seq = 0
        self.arrays = tuple(
            np.empty([int(d) for d in spec["shape"]],
                     np.dtype(spec["dtype"]))
            for spec in begin["arrays"])
        self.L = int(begin["arrays"][0]["shape"][0])

    def add_chunk(self, header: dict, blob: bytes) -> None:
        if int(header["epoch"]) != self.epoch:
            raise ValueError(
                f"config-epoch mismatch inside one shipment: chunk "
                f"{header['epoch']} vs ship_begin {self.epoch}")
        seq = int(header["seq"])
        if seq != self.next_seq or seq >= self.n_chunks:
            raise ValueError(f"chunk {seq} out of order (expected "
                             f"{self.next_seq} of {self.n_chunks})")
        lo, hi = int(header["layer_lo"]), int(header["layer_hi"])
        if not 0 <= lo < hi <= self.L:
            raise ValueError(f"chunk layer range [{lo},{hi}) outside "
                             f"[0,{self.L})")
        if zlib.crc32(blob) & 0xFFFFFFFF != int(header["crc"]):
            raise ValueError(f"chunk {seq} checksum mismatch")
        off = 0
        for arr in self.arrays:
            per = arr.nbytes // self.L
            n = per * (hi - lo)
            if off + n > len(blob):
                raise ValueError(f"chunk {seq} blob shorter than its "
                                 "layer range")
            arr[lo:hi] = np.frombuffer(
                blob[off:off + n], arr.dtype).reshape(
                    (hi - lo,) + arr.shape[1:])
            off += n
        if off != len(blob):
            raise ValueError(f"chunk {seq} carries {len(blob) - off} "
                             "trailing bytes")
        self.next_seq = seq + 1

    def finish(self, end: dict) -> Shipment:
        if self.next_seq != self.n_chunks \
                or int(end["n_chunks"]) != self.n_chunks:
            raise ValueError(
                f"shipment ended after {self.next_seq} of "
                f"{self.n_chunks} chunks")
        b = self.begin
        return Shipment(
            epoch=self.epoch, dtype=b["dtype"],
            page_size=int(b["page_size"]), n_tokens=int(b["n_tokens"]),
            n_written=int(b["n_written"]), first_tok=int(b["first_tok"]),
            pages=[int(p) for p in b["pages"]], arrays=self.arrays,
            handoff=dict(b.get("handoff") or {}))


class PageStream:
    """One connected transfer socket: framed sends plus the
    partial-frame timeout-resume recv (the ControlClient._rbuf
    discipline, PR 8) — a recv timeout keeps the bytes read so far and
    the next call resumes the SAME frame; multiple frames read in one
    burst stay buffered for subsequent calls."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._rbuf = b""

    def send(self, payload: bytes) -> None:
        send_msg(self._sock, payload)

    def recv(self, timeout: float) -> Optional[bytes]:
        """One frame payload, or None on timeout (partial frame
        buffered for resume). ConnectionError on EOF, ValueError on an
        oversized length prefix — both mean the channel is dead."""
        self._sock.settimeout(timeout)
        try:
            while len(self._rbuf) < LEN.size:
                part = self._sock.recv(65536)
                if not part:
                    raise ConnectionError("transfer peer closed")
                self._rbuf += part
            (n,) = LEN.unpack(self._rbuf[:LEN.size])
            if not 0 < n <= MAX_FRAME_BYTES:
                raise ValueError(
                    f"transfer frame length {n} outside (0, "
                    f"{MAX_FRAME_BYTES}]")
            while len(self._rbuf) < LEN.size + n:
                part = self._sock.recv(65536)
                if not part:
                    raise ConnectionError("transfer peer closed")
                self._rbuf += part
        except socket.timeout:
            return None
        finally:
            try:
                self._sock.settimeout(None)
            except OSError:
                pass
        payload = self._rbuf[LEN.size:LEN.size + n]
        self._rbuf = self._rbuf[LEN.size + n:]
        return payload

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class DisaggPrefillPlane:
    """The prefill half of a disaggregated pair: listens for the
    decode peer, admits forwarded prompts as stock max_new_tokens=1
    requests, and ships each retiring request's pages (the engine's
    _capture_shipment hands them to the per-request ship_sink). One
    peer connection at a time; its reader thread parses requests, its
    writer thread is the ONLY socket writer (shipments queue through
    _sendq from the engine thread)."""

    role = "prefill"
    # every deref of the optional event bus sits behind `is not None`
    # (the disabled-plane guard discipline, machine-checked)
    OPTIONAL_PLANES = ("_events",)
    # channel-thread single-writer state: handler-side entry points may
    # only reach the pending-handle map under the ship lock
    ENGINE_THREAD_ATTRS = {"_ship_pending": "_ship_lock"}
    HANDLER_THREAD_METHODS = ("stop",)

    def __init__(self, engine, bind: Tuple[str, int], token: str,
                 events=None):
        self._engine = engine
        self._bind = bind
        self._token = token
        self._events = events
        self._ship_lock = threading.Lock()
        self._ship_pending: Dict[int, object] = {}   # tag -> handle
        self._sendq: "queue.Queue" = queue.Queue()
        self._stop_ev = threading.Event()
        self._lsock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        # plane-local counters (bench/tests read these; the metric
        # families are process-global and a loopback bench runs both
        # roles in one registry)
        self.stats = {"shipments": 0, "pages": 0, "bytes": 0,
                      "failures": 0}

    def start(self) -> None:
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind(self._bind)
        lsock.listen(1)
        self._lsock = lsock
        self.port = lsock.getsockname()[1]
        self._thread = threading.Thread(
            target=self._serve, name="cake-disagg-prefill", daemon=True)
        self._thread.start()
        log.info("disagg prefill channel listening on %s:%d",
                 self._bind[0], self.port)

    def stop(self) -> None:
        self._stop_ev.set()
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- channel threads ---------------------------------------------------

    def _serve(self) -> None:
        while not self._stop_ev.is_set():
            try:
                self._lsock.settimeout(0.5)
                conn, addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return   # listener closed by stop()
            hello = recv_bounded_msg(conn, HELLO_BYTES,
                                     time.monotonic() + HELLO_TIMEOUT_S)
            if hello is None or not hmac.compare_digest(
                    hello, self._token.encode()):
                log.warning("disagg peer %s failed the token hello",
                            addr)
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            stream = PageStream(conn)
            dead = threading.Event()
            writer = threading.Thread(
                target=self._writer, args=(stream, dead),
                name="cake-disagg-ship", daemon=True)
            writer.start()
            try:
                self._reader(stream, dead)
            finally:
                dead.set()
                writer.join(timeout=5.0)
                stream.close()
                with self._ship_lock:
                    self._ship_pending.clear()

    def _reader(self, stream: PageStream, dead: threading.Event) -> None:
        while not self._stop_ev.is_set() and not dead.is_set():
            try:
                payload = stream.recv(timeout=0.5)
            except (OSError, ValueError):
                return
            if payload is None:
                continue
            try:
                header, _blob = decode_frame(payload)
            except ValueError:
                log.warning("disagg prefill channel: corrupt frame; "
                            "dropping the connection")
                return
            if header.get("t") == "prefill":
                self._admit(header)

    def _admit(self, header: dict) -> None:
        tag = int(header["tag"])
        epoch = int(header.get("epoch", 0))
        try:
            handle = self._engine.submit(
                [int(t) for t in header["ids"]],
                # the prefill engine's whole job is one chunked prefill
                # plus the first sampled token; decode belongs to the
                # peer
                max_new_tokens=1,
                temperature=header.get("temperature"),
                top_p=header.get("top_p"),
                repeat_penalty=header.get("repeat_penalty"),
                prime_penalty_tokens=header.get("prime") or None,
                priority=header.get("priority"),
                ship_sink=lambda ship, _tag=tag, _ep=epoch:
                    self._enqueue_ship(_tag, _ep, ship),
            )
        except Exception as e:  # noqa: BLE001 — refusal rides the wire
            log.warning("disagg prefill admission refused: %r", e)
            self._sendq.put(("fail", tag, repr(e)))
            return
        with self._ship_lock:
            self._ship_pending[tag] = handle

    def _enqueue_ship(self, tag: int, epoch: int,
                      ship: Optional[Shipment]) -> None:
        """ship_sink callback (ENGINE thread, inside _emit): stamp the
        requesting peer's config epoch and queue for the writer. Must
        never raise into retirement."""
        if ship is not None:
            ship.epoch = epoch
        self._sendq.put(("ship", tag, ship))

    def _writer(self, stream: PageStream, dead: threading.Event) -> None:
        while not self._stop_ev.is_set() and not dead.is_set():
            try:
                item = self._sendq.get(timeout=0.2)
            except queue.Empty:
                item = None
            # failure-scan candidates BEFORE draining: the engine
            # enqueues a shipment strictly before req.done is set, so
            # any done handle whose shipment exists is already visible
            # to the drain below — what remains pending afterwards
            # genuinely failed before capture (error/cancel path) and
            # owes the peer a ship_fail
            with self._ship_lock:
                stale = [t for t, h in self._ship_pending.items()
                         if h.finished()]
            try:
                while item is not None:
                    self._send_item(stream, item)
                    try:
                        item = self._sendq.get_nowait()
                    except queue.Empty:
                        item = None
                with self._ship_lock:
                    stale = [t for t in stale
                             if t in self._ship_pending]
                for tag in stale:
                    self._send_item(stream, ("fail", tag,
                                             "prefill failed"))
            except (OSError, ValueError):
                dead.set()
                return

    def _send_item(self, stream: PageStream, item) -> None:
        kind, tag = item[0], item[1]
        with self._ship_lock:
            self._ship_pending.pop(tag, None)
        if kind == "fail" or item[2] is None:
            reason = item[2] if kind == "fail" else "capture failed"
            self.stats["failures"] += 1
            stream.send(encode_frame(
                {"t": "ship_fail", "tag": tag, "reason": str(reason)}))
            return
        ship: Shipment = item[2]
        t0 = time.perf_counter()
        for frame in shipment_frames(ship, tag):
            stream.send(frame)
        dt = time.perf_counter() - t0
        _SHIP_SECONDS.observe(dt)
        _SHIP_TOTAL.labels(dir="out").inc()
        _SHIP_BYTES.labels(dtype=ship.dtype).inc(ship.payload_bytes)
        self.stats["shipments"] += 1
        self.stats["pages"] += ship.n_written
        self.stats["bytes"] += ship.payload_bytes
        if self._events is not None:
            self._events.publish(
                "kv_shipped", pages=ship.n_written,
                bytes=ship.payload_bytes, dtype=ship.dtype,
                wall_s=round(dt, 6))


class DisaggDecodePlane:
    """The decode half: forwards admitted prompts to the prefill peer
    and completes each deferred admission via engine.disagg_complete —
    with the reassembled shipment when it survives the wire, with None
    (local whole-prompt prefill) on peer-down, timeout, refusal or
    corruption. One channel thread owns the socket for both directions;
    request_prefill (handler thread, under the engine's switch lock)
    only enqueues, so a wedged peer can never stall admissions."""

    role = "decode"
    OPTIONAL_PLANES = ("_events",)
    # channel-thread single-writer state: the handler-side entry point
    # may only reach the pending map under the transfer lock
    ENGINE_THREAD_ATTRS = {"_xfer_pending": "_xfer_lock"}
    HANDLER_THREAD_METHODS = ("request_prefill", "stop")

    def __init__(self, engine, peer: Tuple[str, int], token: str,
                 events=None, timeout_s: float = 30.0):
        self._engine = engine
        self._peer = peer
        self._token = token
        self._events = events
        self.timeout_s = timeout_s
        self._xfer_lock = threading.Lock()
        self._xfer_pending: Dict[int, Tuple[int, float]] = {}
        self._next_tag = 0
        self._sendq: "queue.Queue" = queue.Queue()
        self._connected = threading.Event()
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sock: Optional[socket.socket] = None
        self.stats = {"requested": 0, "shipments": 0, "pages": 0,
                      "bytes": 0, "degraded": 0}

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="cake-disagg-decode", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_ev.set()
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._fail_pending("plane stopped")

    # -- handler-thread surface (called under the engine switch lock) -----

    def request_prefill(self, req) -> bool:
        """Forward one admission to the prefill peer. True = deferred
        (disagg_complete will finish it); False = channel down, caller
        admits through the local path immediately. Enqueue-only: no
        socket I/O under the engine's admission lock."""
        if not self._connected.is_set():
            return False
        with self._xfer_lock:
            self._next_tag += 1
            tag = self._next_tag
            self._xfer_pending[tag] = (
                req.rid, time.monotonic() + self.timeout_s)
        self._sendq.put(encode_frame({
            "t": "prefill", "tag": tag,
            "ids": [int(t) for t in req.prompt_ids],
            "temperature": req.temperature, "top_p": req.top_p,
            "repeat_penalty": req.repeat_penalty,
            "prime": [int(t) for t in req.prime_tokens],
            "priority": req.priority,
            "epoch": self._engine.config_epoch,
        }))
        self.stats["requested"] += 1
        return True

    # -- channel thread ----------------------------------------------------

    def _run(self) -> None:
        backoff = 0.5
        while not self._stop_ev.is_set():
            try:
                sock = socket.create_connection(self._peer, timeout=5.0)
            except OSError:
                self._stop_ev.wait(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            backoff = 0.5
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            stream = PageStream(sock)
            try:
                send_msg(sock, self._token.encode())
            except OSError:
                stream.close()
                continue
            self._sock = sock
            self._connected.set()
            log.info("disagg decode channel connected to %s:%d",
                     *self._peer)
            try:
                self._pump(stream)
            finally:
                self._connected.clear()
                self._sock = None
                stream.close()
                self._fail_pending("transfer channel dropped")

    def _pump(self, stream: PageStream) -> None:
        asm: Dict[int, ShipmentAssembler] = {}
        while not self._stop_ev.is_set():
            while True:
                try:
                    stream.send(self._sendq.get_nowait())
                except queue.Empty:
                    break
                except OSError:
                    return
            try:
                payload = stream.recv(timeout=0.2)
            except (OSError, ValueError, ConnectionError):
                return
            if payload is not None:
                try:
                    self._dispatch(asm, payload)
                except ValueError:
                    log.warning("disagg decode channel: corrupt "
                                "frame; dropping the connection")
                    return
            self._expire()

    def _dispatch(self, asm: Dict[int, ShipmentAssembler],
                  payload: bytes) -> None:
        header, blob = decode_frame(payload)
        t = header.get("t")
        tag = int(header.get("tag", -1))
        if t == "ship_begin":
            try:
                asm[tag] = ShipmentAssembler(header)
            except (ValueError, KeyError, TypeError) as e:
                log.warning("refused shipment (tag %d): %s", tag, e)
                note_adopt("checksum" if "checksum" in str(e)
                           else "geometry")
                self._resolve(tag, None)
        elif t == "ship_chunk":
            a = asm.get(tag)
            if a is None:
                return   # already refused; drain the rest silently
            try:
                a.add_chunk(header, blob)
            except (ValueError, KeyError, TypeError) as e:
                log.warning("refused shipment chunk (tag %d): %s",
                            tag, e)
                asm.pop(tag, None)
                note_adopt("epoch" if "epoch" in str(e)
                           else "checksum")
                self._resolve(tag, None)
        elif t == "ship_end":
            a = asm.pop(tag, None)
            if a is None:
                return
            try:
                ship = a.finish(header)
            except (ValueError, KeyError, TypeError) as e:
                log.warning("refused shipment end (tag %d): %s", tag, e)
                note_adopt("checksum")
                self._resolve(tag, None)
                return
            _SHIP_TOTAL.labels(dir="in").inc()
            self.stats["shipments"] += 1
            self.stats["pages"] += ship.n_written
            self.stats["bytes"] += ship.payload_bytes
            self._resolve(tag, ship)
        elif t == "ship_fail":
            log.info("prefill peer failed tag %d: %s", tag,
                     header.get("reason"))
            note_adopt("degraded")
            self._resolve(tag, None)

    def _expire(self) -> None:
        now = time.monotonic()
        with self._xfer_lock:
            late = [tag for tag, (_rid, dl) in
                    self._xfer_pending.items() if dl < now]
        for tag in late:
            log.warning("disagg shipment tag %d timed out after "
                        "%.1fs; degrading to local prefill",
                        tag, self.timeout_s)
            note_adopt("timeout")
            self._resolve(tag, None)

    def _resolve(self, tag: int, ship: Optional[Shipment]) -> None:
        with self._xfer_lock:
            ent = self._xfer_pending.pop(tag, None)
        if ent is None:
            return   # duplicate / expired / unknown tag
        rid = ent[0]
        if ship is None:
            self.stats["degraded"] += 1
            if self._events is not None:
                self._events.publish("kv_ship_degraded", rid=rid)
        self._engine.disagg_complete(rid, ship)

    def _fail_pending(self, why: str) -> None:
        with self._xfer_lock:
            tags = list(self._xfer_pending)
        for tag in tags:
            note_adopt("degraded")
            self._resolve(tag, None)
        if tags:
            log.warning("disagg decode: degraded %d pending "
                        "request(s) to local prefill (%s)",
                        len(tags), why)


def build_disagg_plane(engine, role: str, peer: str, token: str,
                       events=None, timeout_s: float = 30.0):
    """Engine-side constructor: parse the peer address and build the
    role's plane. Loud-parse discipline: a malformed role/peer is a
    startup ValueError, never a silently-dead channel."""
    if role not in ("prefill", "decode"):
        raise ValueError(
            f"--disagg must be prefill or decode, got {role!r}")
    host, sep, port_s = (peer or "").rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"--disagg-peer must be host:port, got {peer!r}")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"--disagg-peer port {port_s!r} is not an integer")
    if not token:
        raise ValueError(
            "--disagg needs a shared channel token: set "
            "$CAKE_DISAGG_TOKEN on both engines")
    if role == "prefill":
        return DisaggPrefillPlane(engine, (host, port), token,
                                  events=events)
    return DisaggDecodePlane(engine, (host, port), token,
                             events=events, timeout_s=timeout_s)
