"""KV-cache tiering: quantized KV pages + host-RAM spill.

The paged serving pool (models/llama/paged.py) treats the PAGE as its
unit of allocation; this package makes the page the unit of two more
things:

  * quantization (`quantized_pool.py`): an int8 page pool with
    per-page, per-kv-head symmetric scales — pool bytes drop ~4x vs
    f32 (~2x vs bf16), so the same HBM budget holds proportionally
    more resident decode streams;
  * tiering (`host_tier.py`): an LRU host-RAM spill store behind the
    refcounted PageAllocator — cold shared-prefix pages and preempted
    victims' pages stream out to pinned host memory and back on
    demand, instead of being discarded and recomputed.
"""

from cake_tpu.kv.host_tier import HostTier
from cake_tpu.kv.quantized_pool import (
    QuantPool, QuantizedPagedKVCache, dequantize_pages,
)

__all__ = [
    "HostTier",
    "QuantPool",
    "QuantizedPagedKVCache",
    "dequantize_pages",
]
