"""KV-cache tiering: quantized KV pages + host-RAM spill + transfer.

The paged serving pool (models/llama/paged.py) treats the PAGE as its
unit of allocation; this package makes the page the unit of three more
things:

  * quantization (`quantized_pool.py`): int8 and nibble-packed int4
    page pools with per-page, per-kv-head symmetric scales — pool
    bytes drop ~4x (int8) / ~8x (int4) vs f32, so the same HBM budget
    holds proportionally more resident decode streams;
  * tiering (`host_tier.py`): an LRU host-RAM spill store behind the
    refcounted PageAllocator — cold shared-prefix pages, preempted
    victims' pages, and (under pool pressure) actively-decoding
    streams' pages stream out to pinned host memory and back on
    demand, instead of being discarded and recomputed;
  * transfer (`transfer.py`): disaggregated prefill/decode — a
    token-gated, checksummed page channel ships raw pool slices +
    scale sidecars dtype-blind between a prefill engine and a decode
    engine (`--disagg {prefill,decode}`), quantized pages moving
    ~4x/~8x fewer bytes than f32 for the same prefix.
"""

from cake_tpu.kv.host_tier import HostTier
from cake_tpu.kv.quantized_pool import (
    Int4PagedKVCache, Int4Pool, QuantPool, QuantizedPagedKVCache,
    dequantize_pages,
)
from cake_tpu.kv.transfer import (
    DisaggDecodePlane, DisaggPrefillPlane, PageStream, Shipment,
    ShipmentAssembler, build_disagg_plane, decode_frame, encode_frame,
    shipment_frames,
)

__all__ = [
    "HostTier",
    "Int4PagedKVCache",
    "Int4Pool",
    "QuantPool",
    "QuantizedPagedKVCache",
    "dequantize_pages",
    "DisaggDecodePlane",
    "DisaggPrefillPlane",
    "PageStream",
    "Shipment",
    "ShipmentAssembler",
    "build_disagg_plane",
    "decode_frame",
    "encode_frame",
    "shipment_frames",
]
