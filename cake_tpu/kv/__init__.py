"""KV-cache tiering: quantized KV pages + host-RAM spill.

The paged serving pool (models/llama/paged.py) treats the PAGE as its
unit of allocation; this package makes the page the unit of two more
things:

  * quantization (`quantized_pool.py`): int8 and nibble-packed int4
    page pools with per-page, per-kv-head symmetric scales — pool
    bytes drop ~4x (int8) / ~8x (int4) vs f32, so the same HBM budget
    holds proportionally more resident decode streams;
  * tiering (`host_tier.py`): an LRU host-RAM spill store behind the
    refcounted PageAllocator — cold shared-prefix pages, preempted
    victims' pages, and (under pool pressure) actively-decoding
    streams' pages stream out to pinned host memory and back on
    demand, instead of being discarded and recomputed.
"""

from cake_tpu.kv.host_tier import HostTier
from cake_tpu.kv.quantized_pool import (
    Int4PagedKVCache, Int4Pool, QuantPool, QuantizedPagedKVCache,
    dequantize_pages,
)

__all__ = [
    "HostTier",
    "Int4PagedKVCache",
    "Int4Pool",
    "QuantPool",
    "QuantizedPagedKVCache",
    "dequantize_pages",
]
