"""Host-RAM spill tier for the paged KV pool.

A fleet serving millions of users is mostly warm shared prefixes and
parked work — but today a prefix (page-granular sharing, PR 4) or a
preemption victim's progress (PR 5) survives only while it holds HBM
pages. The host tier gives the refcounted PageAllocator a second level:
page contents (raw pool slices — int8 pages + scales, or f32/bf16
pages; the tier is dtype-blind) are `jax.device_get` into host numpy on
`spill`, the device pages free for new admissions, and `restore`
streams them back into freshly-allocated pages on demand — a resumed
victim decodes from where it stopped instead of recomputing prefill,
and a cold prefix re-maps instead of re-prefilling.

Capacity is counted in PAGES (`--kv-host-pages N`); an over-capacity
`put` evicts least-recently-used entries first (everything here is
recomputable, so eviction is loss of a shortcut, never of data). The
ENGINE thread owns all calls that pair with allocator/table mutations —
the tier itself only moves bytes and keeps the LRU map.

Metrics (obs/metrics.py registry; also refreshed at scrape by
obs/steps.refresh_page_gauges):
  cake_kv_host_pages{state,dtype}  gauge    used | free host pages
  cake_kv_spill_total{dir}         counter  spill | restore page moves
  cake_kv_resident_spills_total    counter  decode-resident streams
                                            parked under pool pressure
  cake_kv_spill_seconds            histogram device<->host copy wall
  cake_kv_pool_bytes{tier,dtype}   gauge    device | host bytes
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax
import numpy as np

from cake_tpu.obs import metrics as obs_metrics

log = logging.getLogger(__name__)

_HOST_PAGES = obs_metrics.gauge(
    "cake_kv_host_pages",
    "Host-tier KV pages by state (used = spilled pages resident in "
    "host RAM, free = remaining --kv-host-pages capacity) and pool "
    "storage dtype",
    labelnames=("state", "dtype"))
_SPILLS = obs_metrics.counter(
    "cake_kv_spill_total",
    "KV pages moved across the HBM/host boundary, by direction "
    "(spill = device->host, restore = host->device)",
    labelnames=("dir",))
_RESIDENT_SPILLS = obs_metrics.counter(
    "cake_kv_resident_spills_total",
    "Actively-decoding streams parked in the host tier because the "
    "pool could not admit a new request (decode-resident spill; "
    "preemption victims and cold prefixes count in cake_kv_spill_total "
    "only)")
_SPILL_SECONDS = obs_metrics.histogram(
    "cake_kv_spill_seconds",
    "Wall seconds per spill/restore page movement (device_get or "
    "scatter-back, engine-thread)")
_POOL_BYTES = obs_metrics.gauge(
    "cake_kv_pool_bytes",
    "KV pool bytes resident per tier (device = the paged pool incl. "
    "int8/int4 scale sidecars, host = spilled pages in RAM) and pool "
    "storage dtype",
    labelnames=("tier", "dtype"))


def pool_dtype_name(cache) -> str:
    """Storage-dtype label value for a paged cache: quantized pools
    report their logical precision (a packed int4 pool is uint8-backed
    but stores int4 values), plain pools their array dtype. The ONE
    source for the {dtype} label on cake_kv_pool_bytes /
    cake_kv_host_pages."""
    k = cache.k
    if hasattr(k, "q"):            # QuantPool / Int4Pool
        return "int4" if k.q.dtype == np.uint8 else "int8"
    return np.dtype(k.dtype).name


def note_resident_spill() -> None:
    """Count one decode-resident stream parked in the host tier — the
    engine's _spill_resident_stream seam; keeps the counter global
    module-private."""
    _RESIDENT_SPILLS.inc()


def refresh_gauges(cache, tier: Optional["HostTier"]) -> None:
    """Scrape-time refresh of every cake_kv_* gauge — the PUBLIC seam
    for obs/steps.refresh_page_gauges, so the metric globals above stay
    module-private. cache is the engine's paged pool (device tier:
    memory_bytes sums quantized pools + scale sidecars per dtype); tier
    is the engine's HostTier or None when --kv-host-pages is unset.
    The {dtype} label value is derived here from the live cache — host
    entries always match the device pool's dtype (a reconfigure drops
    entries on any storage change), so one name labels both tiers."""
    name = pool_dtype_name(cache)
    _POOL_BYTES.labels("device", name).set(cache.memory_bytes())
    if tier is not None:
        tier.dtype_name = name
        tier._set_gauges()


@dataclass
class SpilledPages:
    """One spill entry: the raw page contents + resume metadata."""

    n_pages: int
    # pool slices, device layout preserved: for a quantized pool
    # (k_q, k_scale, v_q, v_scale), else (k, v) — restore scatters
    # them back verbatim, so a host round trip is bit-identical
    arrays: Tuple[np.ndarray, ...]
    kind: str = "pages"            # "victim" | "prefix"
    # victim resume state (engine mirrors at preemption time)
    pos: int = 0
    last_tok: int = 0
    n_prefix_tokens: int = 0
    meta: dict = field(default_factory=dict)

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.arrays)


class HostTier:
    """LRU store of spilled KV pages, capacity-bounded in pages."""

    # cakelint guards discipline: the event bus is an optional plane
    OPTIONAL_PLANES = ("_events",)

    def __init__(self, capacity_pages: int, page_bytes: int = 0,
                 events=None, dtype_name: str = "float32"):
        if capacity_pages < 1:
            raise ValueError(
                f"--kv-host-pages {capacity_pages} must be >= 1")
        self.capacity = capacity_pages
        self.page_bytes = page_bytes
        # {dtype} gauge label: set at construction from the engine's
        # storage name, re-derived from the live cache at every scrape
        # (refresh_gauges is the source of truth)
        self.dtype_name = dtype_name
        self._entries: "OrderedDict[object, SpilledPages]" = OrderedDict()
        self._used = 0
        self.spills = 0
        self.restores = 0
        self.evictions = 0
        # obs/events.EventBus (None = disabled plane, one attribute
        # test per publish site): put/pop are THE spill/restore seams
        # every caller funnels through, so kv_spill/kv_restore events
        # published here cover victim AND cold-prefix movements
        self._events = events
        self._set_gauges()

    def _publish(self, type: str, key, entry: SpilledPages) -> None:
        if self._events is None:
            # belt+braces with the callers' own guards: the helper must
            # hold the disabled-plane contract even for a future caller
            # that forgets its guard (cakelint `guards` pins this)
            return
        # ("victim", rid) keys link the event to its request; prefix
        # entries carry the pid as a field instead (no rid exists)
        rid = pid = None
        if isinstance(key, tuple) and len(key) == 2:
            if key[0] == "victim":
                rid = key[1]
            elif key[0] == "prefix":
                pid = key[1]
        self._events.publish(type, rid=rid, kind=entry.kind,
                             pages=entry.n_pages, pid=pid)

    # -- accounting --------------------------------------------------------

    @property
    def used_pages(self) -> int:
        return self._used

    @property
    def free_pages(self) -> int:
        return self.capacity - self._used

    @property
    def used_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def can_hold(self, n_pages: int) -> bool:
        """Whether n_pages could be stored at all (evicting colder
        entries if needed) — the engine's spill-vs-recompute gate."""
        return n_pages <= self.capacity

    def _set_gauges(self) -> None:
        try:
            _HOST_PAGES.labels("used", self.dtype_name).set(self._used)
            _HOST_PAGES.labels("free", self.dtype_name).set(
                self.free_pages)
            _POOL_BYTES.labels("host", self.dtype_name).set(
                self.used_bytes)
        except Exception:  # noqa: BLE001 — telemetry never fails serving
            log.debug("host tier gauge update failed", exc_info=True)

    # -- store -------------------------------------------------------------

    def put(self, key, entry: SpilledPages) -> bool:
        """Store an entry, evicting LRU entries until it fits; False
        (and no mutation) when it can never fit."""
        if entry.n_pages > self.capacity:
            return False
        self.drop(key)
        while self._used + entry.n_pages > self.capacity:
            old_key, old = self._entries.popitem(last=False)
            self._used -= old.n_pages
            self.evictions += 1
            log.debug("host tier: evicted %r (%d pages)", old_key,
                      old.n_pages)
        self._entries[key] = entry
        self._used += entry.n_pages
        self.spills += entry.n_pages
        _SPILLS.labels("spill").inc(entry.n_pages)
        if self._events is not None:
            self._publish("kv_spill", key, entry)
        self._set_gauges()
        return True

    def peek(self, key) -> Optional[SpilledPages]:
        """Entry lookup WITHOUT removal; refreshes LRU recency."""
        e = self._entries.get(key)
        if e is not None:
            self._entries.move_to_end(key)
        return e

    def pop(self, key, restored: bool = True) -> Optional[SpilledPages]:
        """Remove and return an entry (restored=True counts it as a
        restore; False is a plain discard)."""
        e = self._entries.pop(key, None)
        if e is None:
            return None
        self._used -= e.n_pages
        if restored:
            self.restores += e.n_pages
            _SPILLS.labels("restore").inc(e.n_pages)
            if self._events is not None:
                self._publish("kv_restore", key, e)
        self._set_gauges()
        return e

    def drop(self, key) -> None:
        self.pop(key, restored=False)

    def clear(self) -> None:
        self._entries.clear()
        self._used = 0
        self._set_gauges()

    def keys(self) -> List[object]:
        return list(self._entries.keys())

    # -- device <-> host movement -----------------------------------------

    @staticmethod
    def fetch_pages(cache, pages) -> Tuple[np.ndarray, ...]:
        """device_get the contents of `pages` from a paged cache (plain
        or quantized pool) as host numpy, ONE batched transfer. Layout:
        quantized -> (k_q, k_scale, v_q, v_scale), else (k, v); every
        array keeps its [L, n, ...] pool slice shape so restore is a
        verbatim scatter (bit-identical round trip)."""
        import jax.numpy as jnp
        idx = jnp.asarray(list(pages), jnp.int32)
        k, v = cache.k, cache.v
        if hasattr(k, "q"):       # QuantPool
            devs = (jnp.take(k.q, idx, axis=1),
                    jnp.take(k.scale, idx, axis=1),
                    jnp.take(v.q, idx, axis=1),
                    jnp.take(v.scale, idx, axis=1))
        else:
            devs = (jnp.take(k, idx, axis=1), jnp.take(v, idx, axis=1))
        t0 = time.perf_counter()
        host = jax.device_get(devs)
        _SPILL_SECONDS.observe(time.perf_counter() - t0)
        return tuple(np.asarray(a) for a in host)

    @staticmethod
    def install_pages(cache, pages, arrays: Tuple[np.ndarray, ...]):
        """Scatter spilled contents back into freshly-allocated pages
        of (a possibly different generation of) the pool; returns the
        updated cache. Inverse of fetch_pages — same array order."""
        import jax.numpy as jnp
        idx = jnp.asarray(list(pages), jnp.int32)
        t0 = time.perf_counter()
        k, v = cache.k, cache.v
        if hasattr(k, "q"):       # QuantPool
            kq, ks, vq, vs = arrays
            cache = cache._replace(
                k=k._replace(q=k.q.at[:, idx].set(jnp.asarray(kq)),
                             scale=k.scale.at[:, idx].set(
                                 jnp.asarray(ks))),
                v=v._replace(q=v.q.at[:, idx].set(jnp.asarray(vq)),
                             scale=v.scale.at[:, idx].set(
                                 jnp.asarray(vs))),
            )
        else:
            hk, hv = arrays
            cache = cache._replace(
                k=k.at[:, idx].set(jnp.asarray(hk, k.dtype)),
                v=v.at[:, idx].set(jnp.asarray(hv, v.dtype)),
            )
        # the scatter dispatches asynchronously — without the barrier
        # every restore sample would time lazy dispatch (~us) while the
        # actual host->device copy runs inside the next jitted step,
        # making restores look free next to the blocking device_get in
        # fetch_pages. Restores are rare (preempt resume / prefix hit),
        # so the lost overlap is cheap next to an honest histogram.
        jax.block_until_ready((cache.k, cache.v))
        _SPILL_SECONDS.observe(time.perf_counter() - t0)
        return cache
