"""int8/int4 KV page pools with per-page, per-kv-head scales.

Decode is HBM-bandwidth-bound and the KV cache is the growing term
(BENCH_MEASURED: int8 *weights* already run at 1.6x the bf16 roofline;
the 32-slot config collapses to 151 tok/s from cache thrash). Storing
KV pages as int8 with one symmetric scale per (layer, page, kv head)
cuts pool bytes ~4x vs f32 — the same `--kv-pages` byte budget admits
proportionally more resident streams — while attention reads dequantize
in registers exactly like `ops/quant.py` weight-only matmuls.

Layout (the paged pool's, with a scale sidecar):

  pool.q:     [L, N_pages, page, KV, hd] int8
  pool.scale: [L, N_pages, KV]           f32

The scale is PER PAGE, which is what makes spill/restore trivial (a
page + its scale row is self-contained) but means incremental writes
must keep the already-quantized page consistent:

  * whole-window writes (prompt prefill: pages fully overwritten) set
    the page's scale fresh from the window's amax;
  * incremental writes (decode tokens, chunk windows at arbitrary
    offsets) GATHER the touched pages, grow the scale monotonically
    (new_scale = max(old, amax(new)/127)), RE-quantize the resident
    int8 values by the ratio old/new (one extra rounding, bounded by
    half a step of the new scale), write the new tokens, and scatter
    back. The engine zeroes a page's scales at allocation so a fresh
    page's first write always sets its own scale instead of inheriting
    a previous occupant's.

The INT4 variant (`Int4Pool`) halves the bytes again: a page stores
nibble-packed values (the `ops/int4_matmul.pack_int4` group-halves
layout with one group per page — token t rides the LOW nibble of
packed row t, token t + page/2 the HIGH nibble, bias +8) in a
[L, N_pages, page//2, KV, hd] uint8 pool, same f32 scale sidecar, same
monotone-scale RMW discipline at qmax 7. Every writer here is
polymorphic over the two pool types: int4 pages unpack on gather and
repack on scatter, so the quantization math is shared line-for-line.

`QuantPool`/`Int4Pool` are NamedTuples, so a stacked [L, ...] pool
rides `lax.scan` over the block axis unchanged — each layer's body
sees a per-layer pool leaf pair, and the writers in
`models/llama/paged.py` dispatch on the leaf type.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# symmetric int8 range and the amax floor (ops/quant.py convention)
_QMAX = 127.0
# symmetric int4 range: clip to [-7, 7] so the +8 packing bias keeps
# every value a strict nibble (ops/int4_matmul convention)
_QMAX4 = 7.0
_EPS = 1e-8


class QuantPool(NamedTuple):
    """One int8 page pool half (k or v): values + per-page scales.

    q:     int8, [(L,) N_pages, page, KV, hd]
    scale: f32,  [(L,) N_pages, KV]
    """

    q: jnp.ndarray
    scale: jnp.ndarray


class Int4Pool(NamedTuple):
    """One int4 page pool half (k or v): nibble-packed values + scales.

    q:     uint8, [(L,) N_pages, page//2, KV, hd] — two tokens per
           byte: token t in the low nibble of packed row t, token
           t + page//2 in the high nibble, +8 bias (pack_int4 layout
           with one group per page)
    scale: f32,   [(L,) N_pages, KV]
    """

    q: jnp.ndarray
    scale: jnp.ndarray


def pack_page_nibbles(q: jnp.ndarray) -> jnp.ndarray:
    """[..., P, KV, hd] ints in [-8, 7] -> [..., P//2, KV, hd] uint8.

    The `ops/int4_matmul.pack_int4` group-halves layout with g = P (one
    group per page): +8 bias, low nibble = token t, high nibble =
    token t + P//2."""
    P = q.shape[-3]
    v = (q.astype(jnp.int32) + 8) & 0xF
    lo = v[..., : P // 2, :, :]
    hi = v[..., P // 2:, :, :]
    return (lo | (hi << 4)).astype(jnp.uint8)


def unpack_page_nibbles(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of pack_page_nibbles: [..., P//2, KV, hd] uint8 ->
    [..., P, KV, hd] int8 in [-8, 7], token order restored."""
    p32 = packed.astype(jnp.int32)
    lo = (p32 & 0xF) - 8
    hi = (p32 >> 4) - 8
    return jnp.concatenate([lo, hi], axis=-3).astype(jnp.int8)


def _pool_qmax(pool) -> float:
    return _QMAX4 if isinstance(pool, Int4Pool) else _QMAX


def _pool_page(pool) -> int:
    """Tokens per page for a per-layer pool leaf (the packed int4 axis
    stores two tokens per row)."""
    return pool.q.shape[1] * (2 if isinstance(pool, Int4Pool) else 1)


def _gather_q(pool, idx) -> jnp.ndarray:
    """Gather pages `idx` as UNPACKED int values [..., P, KV, hd].
    Out-of-range ids fill with garbage that every caller either masks
    (amax) or drops on the scatter-back."""
    q = jnp.take(pool.q, idx, axis=0, mode="fill", fill_value=0)
    if isinstance(pool, Int4Pool):
        q = unpack_page_nibbles(q)
    return q


def _scatter_q(pool, idx, qw, new_s):
    """Scatter whole pages back (packing int4 values first); OOB ids
    drop. qw: [..., P, KV, hd] ints; new_s: [..., KV] f32."""
    if isinstance(pool, Int4Pool):
        qw = pack_page_nibbles(qw)
    else:
        qw = qw.astype(jnp.int8)
    return pool._replace(
        q=pool.q.at[idx].set(qw, mode="drop"),
        scale=pool.scale.at[idx].set(new_s, mode="drop"),
    )


class QuantizedPagedKVCache(NamedTuple):
    """PagedKVCache with int8 pools + scale sidecars. Same property
    surface as models/llama/paged.PagedKVCache, so the engine and the
    jitted step fns are layout-blind (NamedTuple pytree; the page
    TABLE rides along identically)."""

    k: QuantPool
    v: QuantPool
    table: jnp.ndarray    # [slots, max_pages] int32, -1 = unmapped

    @property
    def page_size(self) -> int:
        return self.k.q.shape[2]

    @property
    def n_pages(self) -> int:
        return self.k.q.shape[1]

    @property
    def max_pages(self) -> int:
        return self.table.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.table.shape[1] * self.k.q.shape[2]

    @classmethod
    def create(cls, config, slots: int, n_pages: int, page_size: int,
               max_seq_len: int) -> "QuantizedPagedKVCache":
        if max_seq_len % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq_len "
                f"{max_seq_len}")
        L = config.num_hidden_layers
        KV = config.num_key_value_heads
        hd = config.head_dim
        shape = (L, n_pages, page_size, KV, hd)
        sshape = (L, n_pages, KV)
        return cls(
            k=QuantPool(q=jnp.zeros(shape, jnp.int8),
                        scale=jnp.zeros(sshape, jnp.float32)),
            v=QuantPool(q=jnp.zeros(shape, jnp.int8),
                        scale=jnp.zeros(sshape, jnp.float32)),
            table=jnp.full((slots, max_seq_len // page_size), -1,
                           jnp.int32),
        )

    def memory_bytes(self) -> int:
        """ACTUAL storage bytes: int8 pools summed per dtype PLUS the
        f32 scale sidecars (the one-dtype `k.nbytes + v.nbytes`
        shortcut undercounts a mixed-dtype pool)."""
        return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(
            (self.k, self.v)))


class Int4PagedKVCache(NamedTuple):
    """PagedKVCache with nibble-packed int4 pools + scale sidecars.
    Same property surface as PagedKVCache / QuantizedPagedKVCache so
    the engine and the jitted step fns stay layout-blind; page_size is
    REAL tokens per page (2x the packed storage axis)."""

    k: Int4Pool
    v: Int4Pool
    table: jnp.ndarray    # [slots, max_pages] int32, -1 = unmapped

    @property
    def page_size(self) -> int:
        return self.k.q.shape[2] * 2

    @property
    def n_pages(self) -> int:
        return self.k.q.shape[1]

    @property
    def max_pages(self) -> int:
        return self.table.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.table.shape[1] * self.k.q.shape[2] * 2

    @classmethod
    def create(cls, config, slots: int, n_pages: int, page_size: int,
               max_seq_len: int) -> "Int4PagedKVCache":
        if max_seq_len % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq_len "
                f"{max_seq_len}")
        if page_size % 2:
            raise ValueError(
                f"int4 KV pages nibble-pack two tokens per byte: "
                f"page_size {page_size} must be even")
        L = config.num_hidden_layers
        KV = config.num_key_value_heads
        hd = config.head_dim
        shape = (L, n_pages, page_size // 2, KV, hd)
        sshape = (L, n_pages, KV)
        return cls(
            k=Int4Pool(q=jnp.zeros(shape, jnp.uint8),
                       scale=jnp.zeros(sshape, jnp.float32)),
            v=Int4Pool(q=jnp.zeros(shape, jnp.uint8),
                       scale=jnp.zeros(sshape, jnp.float32)),
            table=jnp.full((slots, max_seq_len // page_size), -1,
                           jnp.int32),
        )

    def memory_bytes(self) -> int:
        return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(
            (self.k, self.v)))


def page_bytes(config, page_size: int, dtype=jnp.float32) -> int:
    """Storage bytes ONE pool page costs (k + v, all layers, scale
    sidecars included for int8/int4) — the ONE source the bench
    `--kv-tier` byte budget, `memory_bytes`, and the host tier's
    accounting all price pages in."""
    L = config.num_hidden_layers
    KV = config.num_key_value_heads
    hd = config.head_dim
    name = dtype if isinstance(dtype, str) else jnp.dtype(dtype).name
    if name == "int8":
        per = L * page_size * KV * hd * 1 + L * KV * 4
    elif name == "int4":
        per = L * (page_size // 2) * KV * hd * 1 + L * KV * 4
    else:
        per = L * page_size * KV * hd * jnp.dtype(dtype).itemsize
    return 2 * per          # k and v


def _quantize_windows(vals: jnp.ndarray, qmax: float = _QMAX):
    """Quantize whole page windows: vals [..., P, KV, hd] f32-ish ->
    (q int8 same shape in [-qmax, qmax], scale f32 [..., KV]) with
    amax over (P, hd)."""
    v32 = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v32), axis=(-3, -1))            # [..., KV]
    scale = jnp.maximum(amax, _EPS) / qmax
    q = jnp.clip(jnp.round(v32 / scale[..., None, :, None]),
                 -qmax, qmax).astype(jnp.int8)
    return q, scale


def _requant(q_old: jnp.ndarray, ratio: jnp.ndarray,
             qmax: float = _QMAX) -> jnp.ndarray:
    """Re-quantize resident int values after a monotone scale growth:
    q' = round(q * old/new). ratio broadcasts [..., KV] over
    [..., P, KV, hd]."""
    return jnp.clip(
        jnp.round(q_old.astype(jnp.float32) * ratio[..., None, :, None]),
        -qmax, qmax).astype(jnp.int8)


def dequantize_pages(pool, idx: jnp.ndarray,
                     fill_zero: bool = False) -> jnp.ndarray:
    """Gather pages `idx` and dequantize to f32:
    [*idx.shape, P, KV, hd]. fill_zero routes out-of-range ids to a
    zero page (the fold's unmapped-page semantics; an int4 fill page
    unpacks to -8s but its zero scale zeroes the product)."""
    if fill_zero:
        q = jnp.take(pool.q, idx, axis=0, mode="fill", fill_value=0)
        s = jnp.take(pool.scale, idx, axis=0, mode="fill",
                     fill_value=0.0)
    else:
        q = jnp.take(pool.q, idx, axis=0)
        s = jnp.take(pool.scale, idx, axis=0)
    if isinstance(pool, Int4Pool):
        q = unpack_page_nibbles(q)
    return q.astype(jnp.float32) * s[..., None, :, None]


def reset_page_scales(cache, pages):
    """Zero the scales of freshly-allocated pages (host-computed page
    list; one tiny eager scatter per admission, the table_set_slot
    precedent). A fresh page's first incremental write then sets its
    own scale instead of inheriting a previous occupant's amax —
    without this, a page recycled from a large-activation request
    would quantize a new request's small values to ~0."""
    idx = jnp.asarray(list(pages), jnp.int32)
    zeros = jnp.zeros((cache.k.scale.shape[0], idx.shape[0],
                       cache.k.scale.shape[2]), jnp.float32)
    return cache._replace(
        k=cache.k._replace(scale=cache.k.scale.at[:, idx].set(zeros)),
        v=cache.v._replace(scale=cache.v.scale.at[:, idx].set(zeros)),
    )


# -- writers (per-layer pool leaves, models/llama/paged.py contracts) ---------


def qwrite_prompt_pages(pool, vals: jnp.ndarray,
                        table_row: jnp.ndarray, n_real=None):
    """write_prompt_pages over a quantized pool (int8 or int4):
    page-ALIGNED windows
    fully overwrite their pages, so each window quantizes fresh (scale
    from the window's own amax; zero padding cannot raise it) and both
    q and scale scatter in one parallel write. Unmapped windows route
    to the out-of-bounds index and drop.

    n_real (traced scalar) marks the real prompt length: BUCKET padding
    positions carry token-id-0 garbage k/v that is dead data for the
    f32 pool (overwritten by decode before it can be attended) but
    would POISON a fresh page scale here — the scale only grows after
    this write, so a garbage-inflated amax coarsens the page's real
    tokens for the page's whole life. Padding values are zeroed before
    quantization instead."""
    N, P = pool.q.shape[0], _pool_page(pool)
    S = vals.shape[1]
    KV, hd = vals.shape[2], vals.shape[3]
    if n_real is not None:
        live = jnp.arange(S)[None, :, None, None] < n_real
        vals = jnp.where(live, vals, 0)
    n_win = -(-S // P)
    pad = n_win * P - S
    if pad:
        vals = jnp.pad(vals, ((0, 0), (0, pad), (0, 0), (0, 0)))
    pages = table_row[:n_win]
    idx = jnp.where(pages >= 0, pages, N)
    w = vals[0].reshape(n_win, P, KV, hd)
    q, scale = _quantize_windows(w, _pool_qmax(pool))
    return _scatter_q(pool, idx, q, scale)


def qupdate_pool_per_row(pool, vals: jnp.ndarray, pos,
                         active, table):
    """update_pool_per_row over a quantized pool: each active row's
    decode token lands in ONE page — gather that page + scale, grow
    the scale to cover the token, re-quantize residents by old/new,
    write the token, scatter back. Distinct rows own distinct pages so
    the B round-trips are disjoint; inactive/unmapped rows route to
    the out-of-bounds index on both the gather (zero/one fill) and the
    scatter (drop)."""
    N, P = pool.q.shape[0], _pool_page(pool)
    qmax = _pool_qmax(pool)
    B = vals.shape[0]
    rows = jnp.arange(B)
    pages = table[rows, pos // P]
    offs = pos % P
    valid = jnp.logical_and(active, pages >= 0)
    idx = jnp.where(valid, pages, N)
    qs = _gather_q(pool, idx)                           # [B,P,KV,hd]
    ss = jnp.take(pool.scale, idx, axis=0, mode="fill",
                  fill_value=0.0)                       # [B,KV]
    tok = vals[:, 0].astype(jnp.float32)                # [B,KV,hd]
    need = jnp.maximum(jnp.max(jnp.abs(tok), axis=-1), _EPS) / qmax
    new_s = jnp.maximum(ss, need)
    qr = _requant(qs, ss / new_s, qmax)
    qt = jnp.clip(jnp.round(tok / new_s[..., None]),
                  -qmax, qmax).astype(jnp.int8)         # [B,KV,hd]
    mask = (jnp.arange(P)[None, :] == offs[:, None])    # [B,P]
    qw = jnp.where(mask[..., None, None], qt[:, None], qr)
    return _scatter_q(pool, idx, qw, new_s)


def _window_pages_rmw(pool, vals, j_idx, off_idx, wmask_src,
                      idx, touched):
    """Shared gather -> rescale -> overwrite -> scatter core for the
    window writers. vals: [..., C, KV, hd] f32; j_idx/off_idx: window
    page / in-page offset per position; wmask_src: per-position write
    validity; idx: [..., W] gather/scatter page ids (OOB = dropped);
    touched: [..., W] pages that receive >= 1 position."""
    W = idx.shape[-1]
    P = _pool_page(pool)
    qmax = _pool_qmax(pool)
    KV, hd = vals.shape[-2], vals.shape[-1]
    lead = vals.shape[:-3]
    qs = _gather_q(pool, idx)                      # [..., W, P, KV, hd]
    ss = jnp.take(pool.scale, idx, axis=0, mode="fill",
                  fill_value=0.0)                  # [..., W, KV]
    # place the window's values + mask into page coordinates: every
    # (page, offset) target is distinct within a row, so one scatter
    buf = jnp.zeros(lead + (W + 1, P, KV, hd), jnp.float32)
    msk = jnp.zeros(lead + (W + 1, P), bool)
    jj = jnp.where(wmask_src, j_idx, W)            # invalid -> dropped row
    if lead:
        b = jnp.arange(lead[0])[:, None]
        buf = buf.at[b, jj, off_idx].set(vals.astype(jnp.float32))
        msk = msk.at[b, jj, off_idx].set(wmask_src)
    else:
        buf = buf.at[jj, off_idx].set(vals.astype(jnp.float32))
        msk = msk.at[jj, off_idx].set(wmask_src)
    buf, msk = buf[..., :W, :, :, :], msk[..., :W, :]
    amax = jnp.max(jnp.where(msk[..., None, None], jnp.abs(buf), 0.0),
                   axis=(-3, -1))                  # [..., W, KV]
    need = jnp.maximum(amax, _EPS) / qmax
    new_s = jnp.where(touched[..., None], jnp.maximum(ss, need), ss)
    qr = _requant(qs, jnp.where(new_s > 0, ss / jnp.maximum(new_s, _EPS),
                                0.0), qmax)
    qt = jnp.clip(jnp.round(buf / jnp.maximum(new_s, _EPS)[..., None, :,
                                              None]),
                  -qmax, qmax).astype(jnp.int8)
    qw = jnp.where(msk[..., None, None], qt, qr)
    return _scatter_q(pool, idx, qw, new_s)


def qwrite_window_pages(pool, vals: jnp.ndarray,
                        table_row, pos0, n_real=None):
    """write_window_pages over a quantized pool: one C-token window at
    absolute position pos0 (any in-page offset). The window touches at
    most ceil(C/P)+1 consecutive pages — those are gathered, rescaled,
    overwritten at the window's positions, and scattered back.

    n_real (traced scalar) marks the real tokens in the window: the
    chunk path pads the last window to bucket width C with token-id-0
    garbage whose amax would otherwise enter the MONOTONE page scale
    and permanently coarsen the page's real tokens (the batched mixed
    writer already masks by q_len). Padding positions neither write
    nor contribute to the amax, and pages touched only by padding are
    left alone entirely."""
    N, P = pool.q.shape[0], _pool_page(pool)
    C = vals.shape[1]
    max_pages = table_row.shape[0]
    if n_real is None:
        n_real = C
    n_real = jnp.asarray(n_real, jnp.int32)
    W = -(-C // P) + 1
    pos = pos0 + jnp.arange(C)
    pidx = pos // P
    first = pos0 // P
    win_pidx = first + jnp.arange(W)                      # [W]
    pages = table_row[jnp.minimum(win_pidx, max_pages - 1)]
    last = pos0 + jnp.maximum(n_real, 1) - 1
    touched = ((n_real > 0) & (win_pidx <= last // P)
               & (win_pidx < max_pages) & (pages >= 0))
    idx = jnp.where(touched, pages, N)
    # per-position validity mirrors write_window_pages' drop rule
    p_pages = table_row[jnp.minimum(pidx, max_pages - 1)]
    wvalid = ((jnp.arange(C) < n_real)
              & (pidx < max_pages) & (p_pages >= 0))
    return _window_pages_rmw(pool, vals[0], pidx - first, pos % P,
                             wvalid, idx, touched)


def qwrite_windows_pages(pool, vals: jnp.ndarray, pos,
                         q_len, active, table):
    """write_windows_pages over a quantized pool: the batched mixed
    writer — every row's q_len-token window at its own offset, decode
    rows (q_len=1) included. Per row the window spans at most
    ceil(C/P)+1 consecutive pages; rows own disjoint (non-shared)
    pages, so the batched page round-trips never collide."""
    N, P = pool.q.shape[0], _pool_page(pool)
    B, C = vals.shape[0], vals.shape[1]
    max_pages = table.shape[1]
    W = -(-C // P) + 1
    positions = pos[:, None] + jnp.arange(C)[None, :]     # [B, C]
    pidx = positions // P
    first = pos // P                                      # [B]
    win_pidx = first[:, None] + jnp.arange(W)[None, :]    # [B, W]
    pages = jnp.take_along_axis(
        table, jnp.minimum(win_pidx, max_pages - 1), axis=1)
    last_q = jnp.maximum(q_len, 1) - 1
    touched = (active[:, None] & (q_len[:, None] > 0)
               & (win_pidx <= (pos + last_q)[:, None] // P)
               & (win_pidx < max_pages) & (pages >= 0))
    idx = jnp.where(touched, pages, N)
    p_pages = jnp.take_along_axis(
        table, jnp.minimum(pidx, max_pages - 1), axis=1)
    wvalid = ((jnp.arange(C)[None, :] < q_len[:, None])
              & active[:, None] & (pidx < max_pages) & (p_pages >= 0))
    return _window_pages_rmw(pool, vals, pidx - first[:, None],
                             positions % P, wvalid, idx, touched)
