"""int8 KV page pool with per-page, per-kv-head scales.

Decode is HBM-bandwidth-bound and the KV cache is the growing term
(BENCH_MEASURED: int8 *weights* already run at 1.6x the bf16 roofline;
the 32-slot config collapses to 151 tok/s from cache thrash). Storing
KV pages as int8 with one symmetric scale per (layer, page, kv head)
cuts pool bytes ~4x vs f32 — the same `--kv-pages` byte budget admits
proportionally more resident streams — while attention reads dequantize
in registers exactly like `ops/quant.py` weight-only matmuls.

Layout (the paged pool's, with a scale sidecar):

  pool.q:     [L, N_pages, page, KV, hd] int8
  pool.scale: [L, N_pages, KV]           f32

The scale is PER PAGE, which is what makes spill/restore trivial (a
page + its scale row is self-contained) but means incremental writes
must keep the already-quantized page consistent:

  * whole-window writes (prompt prefill: pages fully overwritten) set
    the page's scale fresh from the window's amax;
  * incremental writes (decode tokens, chunk windows at arbitrary
    offsets) GATHER the touched pages, grow the scale monotonically
    (new_scale = max(old, amax(new)/127)), RE-quantize the resident
    int8 values by the ratio old/new (one extra rounding, bounded by
    half a step of the new scale), write the new tokens, and scatter
    back. The engine zeroes a page's scales at allocation so a fresh
    page's first write always sets its own scale instead of inheriting
    a previous occupant's.

`QuantPool` is a NamedTuple, so a stacked [L, ...] pool rides
`lax.scan` over the block axis unchanged — each layer's body sees a
per-layer QuantPool leaf pair, and the writers in
`models/llama/paged.py` dispatch on the leaf type.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# symmetric int8 range and the amax floor (ops/quant.py convention)
_QMAX = 127.0
_EPS = 1e-8


class QuantPool(NamedTuple):
    """One int8 page pool half (k or v): values + per-page scales.

    q:     int8, [(L,) N_pages, page, KV, hd]
    scale: f32,  [(L,) N_pages, KV]
    """

    q: jnp.ndarray
    scale: jnp.ndarray


class QuantizedPagedKVCache(NamedTuple):
    """PagedKVCache with int8 pools + scale sidecars. Same property
    surface as models/llama/paged.PagedKVCache, so the engine and the
    jitted step fns are layout-blind (NamedTuple pytree; the page
    TABLE rides along identically)."""

    k: QuantPool
    v: QuantPool
    table: jnp.ndarray    # [slots, max_pages] int32, -1 = unmapped

    @property
    def page_size(self) -> int:
        return self.k.q.shape[2]

    @property
    def n_pages(self) -> int:
        return self.k.q.shape[1]

    @property
    def max_pages(self) -> int:
        return self.table.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.table.shape[1] * self.k.q.shape[2]

    @classmethod
    def create(cls, config, slots: int, n_pages: int, page_size: int,
               max_seq_len: int) -> "QuantizedPagedKVCache":
        if max_seq_len % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq_len "
                f"{max_seq_len}")
        L = config.num_hidden_layers
        KV = config.num_key_value_heads
        hd = config.head_dim
        shape = (L, n_pages, page_size, KV, hd)
        sshape = (L, n_pages, KV)
        return cls(
            k=QuantPool(q=jnp.zeros(shape, jnp.int8),
                        scale=jnp.zeros(sshape, jnp.float32)),
            v=QuantPool(q=jnp.zeros(shape, jnp.int8),
                        scale=jnp.zeros(sshape, jnp.float32)),
            table=jnp.full((slots, max_seq_len // page_size), -1,
                           jnp.int32),
        )

    def memory_bytes(self) -> int:
        """ACTUAL storage bytes: int8 pools summed per dtype PLUS the
        f32 scale sidecars (the one-dtype `k.nbytes + v.nbytes`
        shortcut undercounts a mixed-dtype pool)."""
        return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(
            (self.k, self.v)))


def page_bytes(config, page_size: int, dtype=jnp.float32) -> int:
    """Storage bytes ONE pool page costs (k + v, all layers, scales
    included for int8) — the unit the bench `--kv-tier` byte budget and
    the host tier's accounting both price pages in."""
    L = config.num_hidden_layers
    KV = config.num_key_value_heads
    hd = config.head_dim
    if dtype == jnp.int8 or dtype == "int8":
        per = L * page_size * KV * hd * 1 + L * KV * 4
    else:
        per = L * page_size * KV * hd * jnp.dtype(dtype).itemsize
    return 2 * per          # k and v


def _quantize_windows(vals: jnp.ndarray):
    """Quantize whole page windows: vals [..., P, KV, hd] f32-ish ->
    (q int8 same shape, scale f32 [..., KV]) with amax over (P, hd)."""
    v32 = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v32), axis=(-3, -1))            # [..., KV]
    scale = jnp.maximum(amax, _EPS) / _QMAX
    q = jnp.clip(jnp.round(v32 / scale[..., None, :, None]),
                 -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def _requant(q_old: jnp.ndarray, ratio: jnp.ndarray) -> jnp.ndarray:
    """Re-quantize resident int8 values after a monotone scale growth:
    q' = round(q * old/new). ratio broadcasts [..., KV] over
    [..., P, KV, hd]."""
    return jnp.clip(
        jnp.round(q_old.astype(jnp.float32) * ratio[..., None, :, None]),
        -_QMAX, _QMAX).astype(jnp.int8)


def dequantize_pages(pool: QuantPool, idx: jnp.ndarray,
                     fill_zero: bool = False) -> jnp.ndarray:
    """Gather pages `idx` and dequantize to f32:
    [*idx.shape, P, KV, hd]. fill_zero routes out-of-range ids to a
    zero page (the fold's unmapped-page semantics)."""
    if fill_zero:
        q = jnp.take(pool.q, idx, axis=0, mode="fill", fill_value=0)
        s = jnp.take(pool.scale, idx, axis=0, mode="fill",
                     fill_value=0.0)
    else:
        q = jnp.take(pool.q, idx, axis=0)
        s = jnp.take(pool.scale, idx, axis=0)
    return q.astype(jnp.float32) * s[..., None, :, None]


def reset_page_scales(cache: QuantizedPagedKVCache,
                      pages) -> QuantizedPagedKVCache:
    """Zero the scales of freshly-allocated pages (host-computed page
    list; one tiny eager scatter per admission, the table_set_slot
    precedent). A fresh page's first incremental write then sets its
    own scale instead of inheriting a previous occupant's amax —
    without this, a page recycled from a large-activation request
    would quantize a new request's small values to ~0."""
    idx = jnp.asarray(list(pages), jnp.int32)
    zeros = jnp.zeros((cache.k.scale.shape[0], idx.shape[0],
                       cache.k.scale.shape[2]), jnp.float32)
    return cache._replace(
        k=cache.k._replace(scale=cache.k.scale.at[:, idx].set(zeros)),
        v=cache.v._replace(scale=cache.v.scale.at[:, idx].set(zeros)),
    )


# -- writers (per-layer pool leaves, models/llama/paged.py contracts) ---------


def qwrite_prompt_pages(pool: QuantPool, vals: jnp.ndarray,
                        table_row: jnp.ndarray,
                        n_real=None) -> QuantPool:
    """write_prompt_pages over a quantized pool: page-ALIGNED windows
    fully overwrite their pages, so each window quantizes fresh (scale
    from the window's own amax; zero padding cannot raise it) and both
    q and scale scatter in one parallel write. Unmapped windows route
    to the out-of-bounds index and drop.

    n_real (traced scalar) marks the real prompt length: BUCKET padding
    positions carry token-id-0 garbage k/v that is dead data for the
    f32 pool (overwritten by decode before it can be attended) but
    would POISON a fresh page scale here — the scale only grows after
    this write, so a garbage-inflated amax coarsens the page's real
    tokens for the page's whole life. Padding values are zeroed before
    quantization instead."""
    N, P = pool.q.shape[0], pool.q.shape[1]
    S = vals.shape[1]
    KV, hd = vals.shape[2], vals.shape[3]
    if n_real is not None:
        live = jnp.arange(S)[None, :, None, None] < n_real
        vals = jnp.where(live, vals, 0)
    n_win = -(-S // P)
    pad = n_win * P - S
    if pad:
        vals = jnp.pad(vals, ((0, 0), (0, pad), (0, 0), (0, 0)))
    pages = table_row[:n_win]
    idx = jnp.where(pages >= 0, pages, N)
    w = vals[0].reshape(n_win, P, KV, hd)
    q, scale = _quantize_windows(w)
    return QuantPool(
        q=pool.q.at[idx].set(q, mode="drop"),
        scale=pool.scale.at[idx].set(scale, mode="drop"),
    )


def qupdate_pool_per_row(pool: QuantPool, vals: jnp.ndarray, pos,
                         active, table) -> QuantPool:
    """update_pool_per_row over a quantized pool: each active row's
    decode token lands in ONE page — gather that page + scale, grow
    the scale to cover the token, re-quantize residents by old/new,
    write the token, scatter back. Distinct rows own distinct pages so
    the B round-trips are disjoint; inactive/unmapped rows route to
    the out-of-bounds index on both the gather (zero/one fill) and the
    scatter (drop)."""
    N, P = pool.q.shape[0], pool.q.shape[1]
    B = vals.shape[0]
    rows = jnp.arange(B)
    pages = table[rows, pos // P]
    offs = pos % P
    valid = jnp.logical_and(active, pages >= 0)
    idx = jnp.where(valid, pages, N)
    qs = jnp.take(pool.q, idx, axis=0, mode="fill",
                  fill_value=0)                         # [B,P,KV,hd]
    ss = jnp.take(pool.scale, idx, axis=0, mode="fill",
                  fill_value=0.0)                       # [B,KV]
    tok = vals[:, 0].astype(jnp.float32)                # [B,KV,hd]
    need = jnp.maximum(jnp.max(jnp.abs(tok), axis=-1), _EPS) / _QMAX
    new_s = jnp.maximum(ss, need)
    qr = _requant(qs, ss / new_s)
    qt = jnp.clip(jnp.round(tok / new_s[..., None]),
                  -_QMAX, _QMAX).astype(jnp.int8)       # [B,KV,hd]
    mask = (jnp.arange(P)[None, :] == offs[:, None])    # [B,P]
    qw = jnp.where(mask[..., None, None], qt[:, None], qr)
    return QuantPool(
        q=pool.q.at[idx].set(qw, mode="drop"),
        scale=pool.scale.at[idx].set(new_s, mode="drop"),
    )


def _window_pages_rmw(pool: QuantPool, vals, j_idx, off_idx, wmask_src,
                      idx, touched):
    """Shared gather -> rescale -> overwrite -> scatter core for the
    window writers. vals: [..., C, KV, hd] f32; j_idx/off_idx: window
    page / in-page offset per position; wmask_src: per-position write
    validity; idx: [..., W] gather/scatter page ids (OOB = dropped);
    touched: [..., W] pages that receive >= 1 position."""
    W = idx.shape[-1]
    P = pool.q.shape[1]
    KV, hd = vals.shape[-2], vals.shape[-1]
    lead = vals.shape[:-3]
    qs = jnp.take(pool.q, idx, axis=0, mode="fill",
                  fill_value=0)                    # [..., W, P, KV, hd]
    ss = jnp.take(pool.scale, idx, axis=0, mode="fill",
                  fill_value=0.0)                  # [..., W, KV]
    # place the window's values + mask into page coordinates: every
    # (page, offset) target is distinct within a row, so one scatter
    buf = jnp.zeros(lead + (W + 1, P, KV, hd), jnp.float32)
    msk = jnp.zeros(lead + (W + 1, P), bool)
    jj = jnp.where(wmask_src, j_idx, W)            # invalid -> dropped row
    if lead:
        b = jnp.arange(lead[0])[:, None]
        buf = buf.at[b, jj, off_idx].set(vals.astype(jnp.float32))
        msk = msk.at[b, jj, off_idx].set(wmask_src)
    else:
        buf = buf.at[jj, off_idx].set(vals.astype(jnp.float32))
        msk = msk.at[jj, off_idx].set(wmask_src)
    buf, msk = buf[..., :W, :, :, :], msk[..., :W, :]
    amax = jnp.max(jnp.where(msk[..., None, None], jnp.abs(buf), 0.0),
                   axis=(-3, -1))                  # [..., W, KV]
    need = jnp.maximum(amax, _EPS) / _QMAX
    new_s = jnp.where(touched[..., None], jnp.maximum(ss, need), ss)
    qr = _requant(qs, jnp.where(new_s > 0, ss / jnp.maximum(new_s, _EPS),
                                0.0))
    qt = jnp.clip(jnp.round(buf / jnp.maximum(new_s, _EPS)[..., None, :,
                                              None]),
                  -_QMAX, _QMAX).astype(jnp.int8)
    qw = jnp.where(msk[..., None, None], qt, qr)
    return QuantPool(
        q=pool.q.at[idx].set(qw, mode="drop"),
        scale=pool.scale.at[idx].set(new_s, mode="drop"),
    )


def qwrite_window_pages(pool: QuantPool, vals: jnp.ndarray,
                        table_row, pos0, n_real=None) -> QuantPool:
    """write_window_pages over a quantized pool: one C-token window at
    absolute position pos0 (any in-page offset). The window touches at
    most ceil(C/P)+1 consecutive pages — those are gathered, rescaled,
    overwritten at the window's positions, and scattered back.

    n_real (traced scalar) marks the real tokens in the window: the
    chunk path pads the last window to bucket width C with token-id-0
    garbage whose amax would otherwise enter the MONOTONE page scale
    and permanently coarsen the page's real tokens (the batched mixed
    writer already masks by q_len). Padding positions neither write
    nor contribute to the amax, and pages touched only by padding are
    left alone entirely."""
    N, P = pool.q.shape[0], pool.q.shape[1]
    C = vals.shape[1]
    max_pages = table_row.shape[0]
    if n_real is None:
        n_real = C
    n_real = jnp.asarray(n_real, jnp.int32)
    W = -(-C // P) + 1
    pos = pos0 + jnp.arange(C)
    pidx = pos // P
    first = pos0 // P
    win_pidx = first + jnp.arange(W)                      # [W]
    pages = table_row[jnp.minimum(win_pidx, max_pages - 1)]
    last = pos0 + jnp.maximum(n_real, 1) - 1
    touched = ((n_real > 0) & (win_pidx <= last // P)
               & (win_pidx < max_pages) & (pages >= 0))
    idx = jnp.where(touched, pages, N)
    # per-position validity mirrors write_window_pages' drop rule
    p_pages = table_row[jnp.minimum(pidx, max_pages - 1)]
    wvalid = ((jnp.arange(C) < n_real)
              & (pidx < max_pages) & (p_pages >= 0))
    return _window_pages_rmw(pool, vals[0], pidx - first, pos % P,
                             wvalid, idx, touched)


def qwrite_windows_pages(pool: QuantPool, vals: jnp.ndarray, pos,
                         q_len, active, table) -> QuantPool:
    """write_windows_pages over a quantized pool: the batched mixed
    writer — every row's q_len-token window at its own offset, decode
    rows (q_len=1) included. Per row the window spans at most
    ceil(C/P)+1 consecutive pages; rows own disjoint (non-shared)
    pages, so the batched page round-trips never collide."""
    N, P = pool.q.shape[0], pool.q.shape[1]
    B, C = vals.shape[0], vals.shape[1]
    max_pages = table.shape[1]
    W = -(-C // P) + 1
    positions = pos[:, None] + jnp.arange(C)[None, :]     # [B, C]
    pidx = positions // P
    first = pos // P                                      # [B]
    win_pidx = first[:, None] + jnp.arange(W)[None, :]    # [B, W]
    pages = jnp.take_along_axis(
        table, jnp.minimum(win_pidx, max_pages - 1), axis=1)
    last_q = jnp.maximum(q_len, 1) - 1
    touched = (active[:, None] & (q_len[:, None] > 0)
               & (win_pidx <= (pos + last_q)[:, None] // P)
               & (win_pidx < max_pages) & (pages >= 0))
    idx = jnp.where(touched, pages, N)
    p_pages = jnp.take_along_axis(
        table, jnp.minimum(pidx, max_pages - 1), axis=1)
    wvalid = ((jnp.arange(C)[None, :] < q_len[:, None])
              & active[:, None] & (pidx < max_pages) & (p_pages >= 0))
    return _window_pages_rmw(pool, vals, pidx - first[:, None],
                             positions % P, wvalid, idx, touched)
