"""Paged speculative decoding — spec rows behind the one front door.

ROADMAP item 3's spec-on-paged-KV step: speculative decoding as a
first-class ROW KIND in the paged serving engine instead of the dense
single-sequence island in models/llama/speculative.py. Draft and
target KV both live in paged pools addressed by the engine's ONE page
allocator (same id space, same budget the admission gate counts); a
stream's gamma-token speculative suffix occupies dedicated suffix
pages that acceptance truncates back to the allocator after every
round; and the acceptance-rate EMA closes the loop through the gamma
tuner (autotune/spec.py), degrading a collapsing stream to plain
decode — never wedging it — with typed spec_round/spec_degraded
events and cake_spec_* metrics.

Layout:
  accept.py — the accept/resample arithmetic (shared verbatim with the
              dense rounds, which re-import it);
  round.py  — spec_round_paged, the one-launch batched draft+verify
              round over paged KV;
  state.py  — SpecState (per-stream pages + acceptance EMA) and
              SpecPlane (the engine's optional `_specp` plane), plus
              the cake_spec_* metric families.
"""

from cake_tpu.spec.accept import (
    advance_row_keys, assemble_sampled, greedy_accept, rejection_accept,
)
from cake_tpu.spec.round import spec_round_paged
from cake_tpu.spec.state import (
    SPEC_DEGRADED, SPEC_ROUNDS, SpecPlane, SpecState,
)

__all__ = [
    "advance_row_keys", "assemble_sampled", "greedy_accept",
    "rejection_accept", "spec_round_paged", "SpecPlane", "SpecState",
    "SPEC_DEGRADED", "SPEC_ROUNDS",
]
