"""The speculative accept/resample arithmetic, shared by every round.

Moved out of models/llama/speculative.py so the PAGED round
(cake_tpu/spec/round.py) and the dense rounds (_spec_round /
spec_round_batched) consume literally the same functions — the subtle
acceptance math (Leviathan et al., 2023 rejection sampling with the
leftover-residual correction) exists exactly once. speculative.py
re-imports these under their historical names, so the dense path's
imports and tests are untouched.

Everything here is branch-free jnp arithmetic on stacked logits —
trace-safe inside any caller's jit, cache-layout agnostic (nothing
touches KV), and shape-polymorphic over the batch dimension.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "advance_row_keys", "greedy_accept", "rejection_accept",
    "assemble_sampled",
]


def advance_row_keys(keys, advance_mask):
    """Per-row PRNG split: returns (keys', subs [B, 2]) where keys'
    advanced only for rows in advance_mask (idle slots and greedy rows
    keep their stream untouched — concurrency must not change a
    request's sampled tokens)."""
    new_keys, subs = jax.vmap(jax.random.split, out_axes=1)(keys)
    return jnp.where(advance_mask[:, None], new_keys, keys), subs


def greedy_accept(drafts, targets):
    """Accepted-draft count per row under exact-match (greedy)
    acceptance: the longest prefix where draft == target argmax."""
    match = drafts == targets[:, : drafts.shape[1]]
    return jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)


def rejection_accept(drafts, d_probs, t_probs, u, gamma: int):
    """Leviathan accept/reject over a [B, gamma] draft burst, plus the
    leftover-residual distribution at the first rejected position r —
    norm(max(0, p_t - p_d)); at r == gamma (all accepted) the bonus
    token samples from the target's own distribution.
    Returns (n_acc [B], resid [B, V])."""
    B = drafts.shape[0]
    idx = drafts[..., None]                            # [B, gamma, 1]
    p_t = jnp.take_along_axis(t_probs[:, :gamma], idx, axis=-1)[..., 0]
    p_d = jnp.take_along_axis(d_probs, idx, axis=-1)[..., 0]
    accept = u < jnp.minimum(1.0, p_t / jnp.maximum(p_d, 1e-20))
    n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                    axis=1)
    r = jnp.minimum(n_acc, gamma)
    row = jnp.arange(B)
    p_t_r = t_probs[row, r]                            # [B, V]
    p_d_r = jnp.where((r < gamma)[:, None],
                      d_probs[row, jnp.minimum(r, gamma - 1)], 0.0)
    resid = jnp.maximum(p_t_r - p_d_r, 0.0)
    resid = resid / jnp.maximum(jnp.sum(resid, -1, keepdims=True),
                                1e-20)
    return n_acc, resid


def assemble_sampled(drafts, correction, n_acc, gamma: int):
    """Per-row output burst for the sampled path: accepted drafts, then
    the correction/bonus token at position n_acc, tail padded with the
    last draft (masked off by the caller's n_emit mask)."""
    return jnp.where(jnp.arange(gamma + 1)[None] ==
                     jnp.minimum(n_acc, gamma)[:, None],
                     correction[:, None],
                     jnp.concatenate([drafts, drafts[:, -1:]], axis=1))
