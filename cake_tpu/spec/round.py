"""One batched propose-verify-accept round over PAGED KV.

The dense engine's spec_round_batched, re-seated on the paged pool:
the draft loop is a lax.scan of gamma+1 ragged paged decode steps
(models/llama/paged.forward_ragged_paged — each step writes the draft
token's KV into the DRAFT pool through the draft table row and attends
it), the verify is ONE mixed-window pass with logits at every position
(paged.verify_window_paged — target KV for positions pos..pos+gamma
scatters into the target row's pages, suffix-extension pages included),
and acceptance is the shared arithmetic in cake_tpu/spec/accept.py.

Cache contract (identical to the dense round): last_tok sits at
absolute `pos` with its KV not yet written in EITHER pool; the round
writes positions pos..pos+gamma in both; positions past the accepted
frontier hold masked garbage that the next round overwrites before
attending (nothing rolls back). The CALLER (serve/engine._do_spec_paged)
must have extended both table rows to cover pos+gamma inclusive —
writes past the mapped pages are silently dropped by the -1 guard,
which would zero an accepted position's KV.

Both pools share one PageAllocator id space (the draft pool is created
with the target pool's page geometry), so this round needs no allocator
knowledge at all: alloc/extend/truncate stay host-side in the engine.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.paged import (
    PagedKVCache, forward_ragged_paged, verify_window_paged,
)
from cake_tpu.spec.accept import (
    advance_row_keys, assemble_sampled, greedy_accept, rejection_accept,
)

__all__ = ["spec_round_paged"]


@partial(jax.jit,
         static_argnames=("t_cfg", "d_cfg", "gamma", "attn"),
         donate_argnames=("t_cache", "d_cache"))
def spec_round_paged(t_params, d_params, t_cache: PagedKVCache,
                     d_cache: PagedKVCache, last_tok, pos, active,
                     keys, temp, t_rope, d_rope,
                     t_cfg: LlamaConfig, d_cfg: LlamaConfig,
                     gamma: int, attn: str = "fold"):
    """One round for EVERY planned spec row in one compiled program.

    last_tok [B, 1] at per-row absolute `pos` (KV unwritten in both
    pools); active [B] marks the spec rows (inactive rows' pages are
    untouched: draft steps carry `active`, the verify window carries
    q_len = 0); keys [B, 2] per-slot PRNG keys (advanced only for
    active sampled rows — the same streams a plain-decode engine would
    consume, so a spec-degraded stream's sampling is unperturbed);
    temp [B] (<= 0 -> greedy row: argmax drafts + exact-match
    acceptance; > 0 -> leftover-residual rejection sampling).
    Returns (out [B, gamma+1] — first n_emit[b] valid, rest -1;
    n_emit [B] (0 for inactive rows); t_cache; d_cache; keys)."""
    greedy = temp <= 0.0
    temp_eff = jnp.where(greedy, 1.0, temp)[:, None]

    def draft_body(carry, _):
        cache, tok, p, keys = carry
        logits, cache = forward_ragged_paged(d_params, tok, cache, p,
                                             active, d_rope, d_cfg,
                                             attn=attn)
        probs = jax.nn.softmax(logits / temp_eff, axis=-1)
        nxt_g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        keys, subs = advance_row_keys(keys, active & ~greedy)
        nxt_s = jax.vmap(jax.random.categorical)(
            subs, logits / temp_eff).astype(jnp.int32)
        nxt = jnp.where(greedy, nxt_g, nxt_s)
        return ((cache, nxt[:, None], p + active, keys),
                (nxt, probs))

    # gamma+1 draft steps: step gamma writes the last draft's KV (an
    # all-accept round needs no patch-up pass); its proposal is unused
    (d_cache, _, _, keys), (drafts_all, d_probs_all) = jax.lax.scan(
        draft_body, (d_cache, last_tok, pos, keys), None,
        length=gamma + 1)
    drafts = drafts_all[:gamma].T                      # [B, gamma]
    d_probs = jnp.swapaxes(d_probs_all[:gamma], 0, 1)  # [B, gamma, V]

    # verify: ONE mixed-window pass scores [last_tok, d_0..d_{g-1}]
    # per row and writes target KV for positions pos..pos+gamma
    tokens_v = jnp.concatenate([last_tok, drafts], axis=1)
    q_len = jnp.where(active, gamma + 1, 0).astype(jnp.int32)
    t_logits, t_cache = verify_window_paged(
        t_params, tokens_v, pos, q_len, active, t_cache, t_rope,
        t_cfg, attn=attn)                              # [B, g+1, V]

    # greedy rows: exact-match acceptance against the target argmax
    targets = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    n_acc_g = greedy_accept(drafts, targets)

    # sampled rows: leftover-residual rejection sampling per row;
    # greedy rows' residual/correction are computed but unused and
    # their keys never advance
    t_probs = jax.nn.softmax(t_logits / temp_eff[..., None], axis=-1)
    keys, subs = advance_row_keys(keys, active & ~greedy)
    u = jax.vmap(lambda k: jax.random.uniform(k, (gamma,)))(subs)
    n_acc_s, resid = rejection_accept(drafts, d_probs, t_probs, u,
                                      gamma)
    keys, subs = advance_row_keys(keys, active & ~greedy)
    correction = jax.vmap(jax.random.categorical)(
        subs, jnp.log(jnp.maximum(resid, 1e-20))).astype(jnp.int32)
    out_s = assemble_sampled(drafts, correction, n_acc_s, gamma)

    n_acc = jnp.where(greedy, n_acc_g, n_acc_s)
    out = jnp.where(greedy[:, None], targets, out_s)
    n_emit = jnp.where(active, n_acc + 1, 0)
    mask = jnp.arange(gamma + 1)[None] < n_emit[:, None]
    out = jnp.where(mask, out, -1)
    return out, n_emit, t_cache, d_cache, keys
