"""Per-stream speculative state + the spec plane's metrics surface.

A paged-spec engine keeps ONE SpecPlane (serve/engine.py `_specp`,
declared in the engine's OPTIONAL_PLANES: None = spec disabled, and
every engine deref sits behind an `is not None` guard). The plane owns
what the device round does not: the draft model, per-slot SpecState
(page bookkeeping + the acceptance EMA the controller loop reads), the
live gamma, and the gamma tuner seam (cake_tpu/autotune/spec.py).

Page accounting contract: a stream's BASE pages stay in the engine's
`_slot_pages` row exactly as for plain decode. Everything speculative —
the draft row's pages and the target row's suffix-extension pages past
the admission allocation — lives in its SpecState and is released by
`engine._release_spec_state` on teardown and by post-round truncation,
so `free_pages + live_pages == n_pages` holds after every round and a
degraded/finished stream leaks nothing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from cake_tpu.obs import metrics as obs_metrics

# paged speculative decoding (cake_tpu/spec): the closed-loop
# observables — the fleet-level acceptance EMA and emitted tokens per
# round that the gamma tuner (autotune/spec.py) steers on, plus round
# and degrade counters. Per-stream EMAs ride spec_round/spec_degraded
# EVENTS (rids never label metrics).
SPEC_ACCEPT_RATIO = obs_metrics.gauge(
    "cake_spec_accept_ratio",
    "EMA of the fraction of drafted tokens the target accepted per "
    "paged speculative round, engine-wide (per-stream EMAs ride "
    "spec_round events)")
SPEC_TOKENS_PER_ROUND = obs_metrics.gauge(
    "cake_spec_tokens_per_round",
    "EMA of tokens emitted per paged speculative round engine-wide "
    "(1 = speculation is paying nothing, gamma+1 = every draft "
    "accepted)")
SPEC_ROUNDS = obs_metrics.counter(
    "cake_spec_rounds_total",
    "Paged speculative draft+verify rounds dispatched (one batched "
    "launch may cover many streams)")
SPEC_DEGRADED = obs_metrics.counter(
    "cake_spec_degraded_total",
    "Paged speculative degrade actions by kind (disabled = a stream "
    "fell back to plain decode on acceptance collapse or repeated "
    "spec.verify faults; shrink_gamma = the tuner narrowed the "
    "engine-wide draft length)",
    labelnames=("action",))

# EMA smoothing for the acceptance/tokens-per-round signals: light
# enough to react within ~10 rounds, heavy enough that one unlucky
# round cannot trip the degrade threshold on its own
EMA_ALPHA = 0.2

# per-stream degrade policy (the engine-wide gamma policy lives in the
# tuner, autotune/spec.py): a stream is disabled — falls back to plain
# decode, spec pages released — when its acceptance EMA sits below the
# floor after the warmup, or after this many CONSECUTIVE spec.verify
# faults. Warmup > 1/EMA_ALPHA so the EMA has largely forgotten its
# first-round seed before it can condemn a stream.
STREAM_ACCEPT_FLOOR = 0.1
STREAM_WARMUP_ROUNDS = 8
DISABLE_AFTER_FAILS = 3


@dataclass
class SpecState:
    """Per-slot speculative bookkeeping (host-side, engine thread).

    Created lazily by the engine once a stream is decoding and
    spec-compatible (`_spec_activate`); torn down with the slot's pages
    (`_release_spec_state`) or on per-stream degrade."""

    rid: int
    # draft-row pages (context base + per-round suffix extensions, one
    # list — the draft pool has no admission row of its own)
    d_pages: List[int] = field(default_factory=list)
    # target-row pages EXTENDING the admission allocation so a round's
    # gamma+1-token window always lands in mapped pages; truncated back
    # to the accepted frontier after every round
    t_suffix_pages: List[int] = field(default_factory=list)
    # acceptance-rate EMA (accepted/proposed per round); None until the
    # first round so the controller can tell "new" from "collapsed"
    accept_ema: Optional[float] = None
    rounds: int = 0
    proposed: int = 0
    accepted: int = 0
    # consecutive spec.verify faults (reset on a clean round): the
    # never-wedge discipline disables spec for the stream, it does not
    # retry forever
    verify_fails: int = 0
    # False = degraded tombstone: the stream decodes plain for the rest
    # of its life, its spec pages already back in the pool. The state
    # stays in the map so slot reuse cannot resurrect speculation for a
    # condemned rid (teardown pops it with the slot).
    enabled: bool = True

    def note_round(self, proposed: int, accepted: int) -> None:
        self.rounds += 1
        self.proposed += proposed
        self.accepted += accepted
        rate = accepted / max(proposed, 1)
        self.accept_ema = (rate if self.accept_ema is None
                           else (1 - EMA_ALPHA) * self.accept_ema
                           + EMA_ALPHA * rate)


class SpecPlane:
    """Engine-side container for paged speculative decoding.

    Single-writer on the ENGINE thread — the per-slot state map and the
    live gamma are read/written only between device steps by the engine
    loop (no handler-thread entry points), which is what the affinity
    declarations below pin for cakelint. The tuner seam is optional
    (None = fixed gamma), guarded per the optional-plane discipline.
    """

    # engine-loop single-writer state: no handler thread reaches these
    # (scrapes read the metrics registry, never the plane)
    ENGINE_THREAD_ATTRS = {
        "spec_streams": None,
        "live_gamma": None,
        "accept_ema": None,
        "tokens_ema": None,
    }
    HANDLER_THREAD_METHODS = ()
    # every deref of the optional gamma tuner sits behind `is not None`
    OPTIONAL_PLANES = ("tuner",)

    def __init__(self, draft_params, draft_config, gamma: int, rope,
                 tuner=None):
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.rope = rope            # draft RopeTables (draft head_dim)
        self.live_gamma = gamma     # LIVE gamma (tuner may shrink it)
        self.gamma0 = gamma
        self.tuner = tuner
        self.spec_streams: Dict[int, SpecState] = {}  # slot -> SpecState
        # engine-wide EMAs behind the two gauges
        self.accept_ema: Optional[float] = None
        self.tokens_ema: Optional[float] = None

    def note_round(self, proposed: int, accepted: int,
                   tokens: int, rows: int) -> None:
        """Fold one batched round's aggregate into the engine-wide
        EMAs + gauges and feed the tuner its steering signal."""
        SPEC_ROUNDS.inc()
        rate = accepted / max(proposed, 1)
        tpr = tokens / max(rows, 1)
        self.accept_ema = (rate if self.accept_ema is None
                           else (1 - EMA_ALPHA) * self.accept_ema
                           + EMA_ALPHA * rate)
        self.tokens_ema = (tpr if self.tokens_ema is None
                           else (1 - EMA_ALPHA) * self.tokens_ema
                           + EMA_ALPHA * tpr)
        SPEC_ACCEPT_RATIO.set(self.accept_ema)
        SPEC_TOKENS_PER_ROUND.set(self.tokens_ema)
        if self.tuner is not None:
            self.tuner.note_round(self.accept_ema)
