"""Topology: user-facing mapping of model layers onto compute resources.

Capability parity with the reference's `cake-core/src/cake/topology.rs`:
a YAML map of node-name -> {host, description, layers: [...]} where text-model
layer lists support range expressions like ``model.layers.0-15`` which expand
into individual layer names (reference: topology.rs:9-11, 50-76; rejects
stop <= start, topology.rs:60-64).

TPU reinterpretation: instead of `host` being a TCP address of a worker
process, a node maps a contiguous block range onto a *pipeline stage* of a
`jax.sharding.Mesh`. `host` is kept for config-compat (and used verbatim when
running against a multi-host JAX runtime), but placement is derived from node
order / explicit `stage:` keys.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

import yaml

# Range expression: everything up to a non-digit, then start-stop.
# Same grammar as the reference regex `^(.+[^\d])(\d+)-(\d+)$` (topology.rs:9-11).
_LAYER_RANGE_PARSER = re.compile(r"^(.+\D)(\d+)-(\d+)$")


def expand_layer_expr(expr: str) -> List[str]:
    """Expand ``model.layers.0-15`` -> [model.layers.0, ..., model.layers.15].

    Non-range expressions pass through unchanged.  Inclusive on both ends,
    matching the reference (topology.rs:66-71).  Raises ValueError when
    stop <= start (topology.rs:60-64).
    """
    m = _LAYER_RANGE_PARSER.match(expr)
    if m is None:
        return [expr]
    prefix, start_s, stop_s = m.groups()
    start, stop = int(start_s), int(stop_s)
    if stop <= start:
        raise ValueError(
            f"invalid range expression '{expr}': stop must be > start"
        )
    return [f"{prefix}{i}" for i in range(start, stop + 1)]


@dataclass
class Node:
    """One entry in the topology: a named owner of a set of layers.

    Reference: `Node` (topology.rs:14-35).  On TPU a node is a pipeline
    stage (or a named device group), not a remote process.
    """

    host: str = ""
    description: str = ""
    layers: List[str] = field(default_factory=list)
    # TPU extensions (optional in YAML):
    stage: Optional[int] = None      # explicit pipeline-stage index
    devices: Optional[List[int]] = None  # device ids within the mesh

    _expanded: Optional[List[str]] = field(default=None, repr=False)

    def expanded_layers(self) -> List[str]:
        """All concrete layer names this node owns (ranges expanded)."""
        if self._expanded is None:
            out: List[str] = []
            for expr in self.layers:
                out.extend(expand_layer_expr(expr))
            self._expanded = out
        return self._expanded

    def owns_layer(self, full_layer_name: str) -> bool:
        """Prefix match, used for weight selection.

        Reference: `is_text_model_layer_owner` (topology.rs:25-34) — a node
        owning `model.layers.3` owns the tensor
        `model.layers.3.self_attn.q_proj.weight`.
        """
        for layer in self.expanded_layers():
            if full_layer_name == layer or full_layer_name.startswith(layer + "."):
                return True
        return False

    def block_indices(self, prefix: str = "model.layers.") -> List[int]:
        """Numeric transformer-block indices owned by this node."""
        out = []
        for layer in self.expanded_layers():
            if layer.startswith(prefix):
                tail = layer[len(prefix):]
                if tail.isdigit():
                    out.append(int(tail))
        return sorted(out)


class Topology:
    """Ordered mapping node-name -> Node.

    Reference: `Topology` (topology.rs:38-105; Deref to HashMap 94-105).
    Iteration order == YAML document order == default stage order.
    """

    def __init__(self, nodes: "Dict[str, Node]"):
        self.nodes: Dict[str, Node] = nodes

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_path(cls, path: str) -> "Topology":
        """Load and validate a topology.yml (reference: topology.rs:43-79)."""
        with open(path, "r") as f:
            raw = yaml.safe_load(f)
        return cls.from_dict(raw or {})

    @classmethod
    def from_dict(cls, raw: dict) -> "Topology":
        nodes: Dict[str, Node] = {}
        for name, spec in raw.items():
            spec = spec or {}
            node = Node(
                host=spec.get("host", ""),
                description=spec.get("description", ""),
                layers=list(spec.get("layers", []) or []),
                stage=spec.get("stage"),
                devices=list(spec["devices"]) if spec.get("devices") else None,
            )
            node.expanded_layers()  # validate ranges eagerly, like from_path
            nodes[name] = node
        return cls(nodes)

    # -- mapping interface --------------------------------------------------

    def __getitem__(self, name: str) -> Node:
        return self.nodes[name]

    def __contains__(self, name: str) -> bool:
        return name in self.nodes

    def __len__(self) -> int:
        return len(self.nodes)

    def __iter__(self) -> Iterator[str]:
        return iter(self.nodes)

    def items(self):
        return self.nodes.items()

    def keys(self):
        return self.nodes.keys()

    def values(self):
        return self.nodes.values()

    # -- queries ------------------------------------------------------------

    def get_node_for_layer(self, layer_name: str) -> Optional[Tuple[str, Node]]:
        """Exact-match lookup of the owner of a concrete layer name.

        Reference: `get_node_for_layer` (topology.rs:82-91).
        """
        for name, node in self.nodes.items():
            if layer_name in node.expanded_layers():
                return name, node
        return None

    def stage_assignments(
        self, num_layers: int, prefix: str = "model.layers."
    ) -> List[Tuple[str, List[int]]]:
        """Ordered (node_name, contiguous block indices) pipeline stages.

        Blocks not claimed by any node are assigned to the first stage
        (mirroring the reference master, which runs unclaimed layers locally —
        llama.rs:205-220 falls back to local Transformer load).
        Validates that each node's blocks are contiguous.
        """
        stages: List[Tuple[str, List[int]]] = []
        claimed = set()
        ordered = sorted(
            self.nodes.items(),
            key=lambda kv: (kv[1].stage if kv[1].stage is not None else 1 << 30),
        ) if any(n.stage is not None for n in self.nodes.values()) else list(self.nodes.items())
        for name, node in ordered:
            blocks = [b for b in node.block_indices(prefix) if b < num_layers]
            if not blocks:
                continue
            if blocks != list(range(blocks[0], blocks[-1] + 1)):
                raise ValueError(
                    f"node '{name}' owns non-contiguous blocks {blocks}; "
                    "pipeline stages must own contiguous ranges"
                )
            overlap = claimed.intersection(blocks)
            if overlap:
                raise ValueError(
                    f"blocks {sorted(overlap)} claimed by multiple nodes"
                )
            claimed.update(blocks)
            stages.append((name, blocks))
        unclaimed = [i for i in range(num_layers) if i not in claimed]
        if unclaimed:
            if stages and claimed:
                # Attach leading unclaimed blocks to a synthetic master stage.
                stages.insert(0, ("master", unclaimed))
                if unclaimed != list(range(unclaimed[0], unclaimed[-1] + 1)):
                    raise ValueError(
                        f"unclaimed blocks {unclaimed} are non-contiguous"
                    )
            else:
                stages = [("master", unclaimed)]
        # order stages by first block so the pipeline walks 0..num_layers
        stages.sort(key=lambda s: s[1][0])
        flat = [b for _, bs in stages for b in bs]
        if flat != list(range(num_layers)):
            raise ValueError(
                f"stage assignment does not cover 0..{num_layers - 1} exactly: {stages}"
            )
        return stages

    def to_dict(self) -> dict:
        out = {}
        for name, node in self.nodes.items():
            spec: dict = {
                "host": node.host,
                "description": node.description,
                "layers": list(node.layers),
            }
            if node.stage is not None:
                spec["stage"] = node.stage
            if node.devices is not None:
                spec["devices"] = list(node.devices)
            out[name] = spec
        return out

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_dict(), sort_keys=False)
