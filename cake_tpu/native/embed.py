"""Embeddable C-ABI bindings — the TPU analog of `cake-ios`.

The reference exports `start_worker(name, model_path, topology_path,
model_type)` to Swift apps through uniffi (cake-ios/src/lib.rs:20-87,
consumed by the iOS worker app, ContentView.swift:50). Here the same
capability — host a cake node inside a non-Python application — is a
C-ABI shared library (`csrc/embed.cpp`) that embeds CPython and calls the
Python entry points in this module:

  cake_tpu_version(out_buf, cap)             -> package version string
  cake_tpu_generate(model_dir, prompt, n,
                    out_buf, cap)            -> one-shot text generation
  cake_tpu_start_worker(name, model_path,
                        topology_path,
                        model_type, address) -> blocking serve loop
                                                (reference signature
                                                 + bind address)

String-returning calls follow the snprintf convention: 0 on success, a
positive required-capacity value when the buffer is too small (truncated
at a UTF-8 boundary), negative on failure (see cake_tpu_last_error).

`build_embed_library()` compiles it on demand with the system g++ and
`python3-config --embed` flags; any C/C++/Swift host can then dlopen it.
This module also holds the Python-side implementations the C shims call,
keeping the C layer to argument marshalling only.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import sysconfig
import threading

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(_HERE, "csrc")
_BUILD = os.path.join(_HERE, "_build")
_SOURCE = "embed.cpp"


def build_embed_library() -> str:
    """Compile libcake_embed (idempotent, hash-keyed). Returns the .so path."""
    os.makedirs(_BUILD, exist_ok=True)
    src = os.path.join(_CSRC, _SOURCE)
    with open(src, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_BUILD, f"libcake_embed_{tag}.so")
    if os.path.exists(so_path):
        return so_path

    include = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    tmp = f"{so_path}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", "-o", tmp, src,
        f"-L{libdir}", f"-Wl,-rpath,{libdir}", f"-lpython{ver}",
        "-lpthread", "-ldl",
    ]
    log.info("building embed library: %s", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"embed library build failed (is the python dev package "
            f"installed?):\n{e.stderr}"
        ) from e
    os.replace(tmp, so_path)
    return so_path


# -- Python-side implementations called from the C shims ---------------------

def version() -> str:
    import cake_tpu
    return cake_tpu.__version__


_masters: dict = {}
_masters_lock = threading.Lock()


def generate(model_dir: str, prompt: str, sample_len: int = 16) -> str:
    """One-shot generation for embedded hosts; returns the generated text.

    The Master (weights + compiled programs) is cached per model_dir so
    repeat calls pay token cost only — the embedded analog of the
    reference's persistent worker process. Serialised under a lock: a
    Master holds mutable chat/decode state, and multithreaded C hosts are
    an expected caller (jax releases the GIL mid-generation, so two
    unsynchronised calls would interleave resets)."""
    from cake_tpu.args import parse_args
    from cake_tpu.master import Master
    from cake_tpu.models.chat import Message

    args, sd_args, _ = parse_args([
        "--model", model_dir, "--prompt", prompt,
        "--sample-len", str(sample_len),
    ])
    with _masters_lock:
        master = _masters.get(model_dir)
        if master is None:
            master = _masters[model_dir] = Master.from_args(args, sd_args)
        else:
            master.reset()
        master.add_message(Message.system(args.system_prompt))
        master.add_message(Message.user(prompt))
        return master.generate_text(lambda t: None, sample_len=sample_len)


def start_worker(name: str, model_path: str, topology_path: str,
                 model_type: str = "text",
                 address: str = "127.0.0.1:10128") -> int:
    """Blocking node loop — signature parity with the reference's uniffi
    export (cake-ios/src/lib.rs:20-28), plus an explicit bind address (the
    reference hardcodes 0.0.0.0:10128; embedding hosts must be able to pick
    the interface/port). On TPU every node runs the same SPMD program, so
    this serves the API (coordinator) or joins the computation
    (non-coordinator) until killed."""
    from cake_tpu.cli import main

    argv = ["--name", name, "--model", model_path,
            "--model-type", model_type, "--api", address]
    if topology_path:
        argv += ["--topology", topology_path]
    return main(argv)
