"""Native (C++) runtime components, built on first use with the system g++.

The compute path is JAX/XLA/Pallas; the runtime around it — checkpoint IO
and the serving scheduler — has native implementations here, mirroring how
the reference leans on native code for its runtime (Candle's kernels,
mmap'd loading; SURVEY.md §2.5). Python fallbacks exist for every
component, so the framework works even where no C++ toolchain does:

  * csrc/safetensors.cpp — mmap'd safetensors reader (zero-copy tensor
    views + madvise prefetch), wrapped in native/safetensors.py
  * csrc/scheduler.cpp — thread-safe continuous-batching scheduler,
    wrapped in native/scheduler.py

The shared object is compiled once into _build/ (keyed on a source hash)
and dlopened via ctypes; no pip, no pybind11, no build system beyond g++.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(_HERE, "csrc")
_BUILD = os.path.join(_HERE, "_build")
_SOURCES = ("safetensors.cpp", "scheduler.cpp")

_lock = threading.Lock()
_lib = None
_lib_error: str | None = None


def _source_hash() -> str:
    h = hashlib.sha256()
    for src in _SOURCES:
        with open(os.path.join(_CSRC, src), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build_library() -> str:
    os.makedirs(_BUILD, exist_ok=True)
    tag = _source_hash()
    so_path = os.path.join(_BUILD, f"libcake_native_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    srcs = [os.path.join(_CSRC, s) for s in _SOURCES]
    tmp = f"{so_path}.{os.getpid()}.tmp"  # per-process; replace is atomic
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, *srcs, "-lpthread"]
    log.info("building native library: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, so_path)  # atomic vs concurrent builders
    return so_path


def _declare(lib) -> None:
    c = ctypes
    # safetensors
    lib.cake_st_open.restype = c.c_void_p
    lib.cake_st_open.argtypes = [c.c_char_p, c.c_char_p, c.c_int]
    lib.cake_st_num_tensors.restype = c.c_int64
    lib.cake_st_num_tensors.argtypes = [c.c_void_p]
    lib.cake_st_name.restype = c.c_char_p
    lib.cake_st_name.argtypes = [c.c_void_p, c.c_int64]
    lib.cake_st_dtype.restype = c.c_char_p
    lib.cake_st_dtype.argtypes = [c.c_void_p, c.c_int64]
    lib.cake_st_ndim.restype = c.c_int32
    lib.cake_st_ndim.argtypes = [c.c_void_p, c.c_int64]
    lib.cake_st_shape.restype = None
    lib.cake_st_shape.argtypes = [c.c_void_p, c.c_int64,
                                  c.POINTER(c.c_int64)]
    lib.cake_st_data.restype = c.POINTER(c.c_uint8)
    lib.cake_st_data.argtypes = [c.c_void_p, c.c_int64,
                                 c.POINTER(c.c_int64)]
    lib.cake_st_prefetch.restype = None
    lib.cake_st_prefetch.argtypes = [c.c_void_p, c.c_int64]
    lib.cake_st_close.restype = None
    lib.cake_st_close.argtypes = [c.c_void_p]
    # scheduler
    lib.cake_sched_create.restype = c.c_void_p
    lib.cake_sched_create.argtypes = [c.c_int32, c.c_int32]
    lib.cake_sched_destroy.restype = None
    lib.cake_sched_destroy.argtypes = [c.c_void_p]
    lib.cake_sched_submit.restype = c.c_int32
    lib.cake_sched_submit.argtypes = [c.c_void_p, c.c_uint64, c.c_int32,
                                      c.c_int32]
    lib.cake_sched_cancel.restype = c.c_int32
    lib.cake_sched_cancel.argtypes = [c.c_void_p, c.c_uint64]
    lib.cake_sched_plan.restype = c.c_int32
    lib.cake_sched_plan.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_uint64), c.POINTER(c.c_int32), c.POINTER(c.c_int32),
        c.POINTER(c.c_uint64), c.POINTER(c.c_int32), c.POINTER(c.c_int32),
    ]
    lib.cake_sched_report.restype = c.c_int32
    lib.cake_sched_report.argtypes = [c.c_void_p, c.c_uint64, c.c_int32,
                                      c.c_int32]
    lib.cake_sched_queue_depth.restype = c.c_int32
    lib.cake_sched_queue_depth.argtypes = [c.c_void_p]
    lib.cake_sched_active.restype = c.c_int32
    lib.cake_sched_active.argtypes = [c.c_void_p]
    lib.cake_sched_completed.restype = c.c_uint64
    lib.cake_sched_completed.argtypes = [c.c_void_p]


def get_library():
    """Build (if needed) and dlopen the native library; None on failure."""
    global _lib, _lib_error
    with _lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        try:
            so_path = _build_library()
            lib = ctypes.CDLL(so_path)
            _declare(lib)
            _lib = lib
        except Exception as e:  # toolchain missing, build error, ...
            _lib_error = str(e)
            log.warning("native library unavailable (%s); "
                        "using Python fallbacks", e)
        return _lib


def is_available() -> bool:
    return get_library() is not None
