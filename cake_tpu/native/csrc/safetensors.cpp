// mmap'd safetensors reader with a C ABI (ctypes-consumed).
//
// Native-runtime counterpart of the reference's weight loading
// (cake-core/src/utils/mod.rs:85-104: VarBuilder::from_mmaped_safetensors):
// the file is mapped read-only, the JSON header parsed once, and tensor
// data exposed as zero-copy pointers into the mapping. madvise() gives the
// kernel sequential/willneed hints so multi-GB checkpoint reads stream at
// disk bandwidth instead of faulting page-by-page while the Python side
// feeds jax.device_put.
//
// Build: g++ -O2 -shared -fPIC (see cake_tpu/native/__init__.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct TensorMeta {
  std::string name;
  std::string dtype;
  std::vector<int64_t> shape;
  int64_t begin = 0;  // relative to data section
  int64_t end = 0;
};

// ---- minimal JSON subset parser (safetensors headers only) ----------------
// Grammar actually used by safetensors: object of
//   name -> {"dtype": str, "shape": [ints], "data_offsets": [int, int]}
// plus optional "__metadata__" -> {str: str}.

struct Parser {
  const char* p;
  const char* end;
  std::string err;

  bool fail(const char* msg) {
    if (err.empty()) err = msg;
    return false;
  }
  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool expect(char c) {
    ws();
    if (p >= end || *p != c) return fail("unexpected character");
    ++p;
    return true;
  }
  bool peek(char c) {
    ws();
    return p < end && *p == c;
  }
  bool string(std::string* out) {
    ws();
    if (p >= end || *p != '"') return fail("expected string");
    ++p;
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\') {
        ++p;
        if (p >= end) return fail("bad escape");
        switch (*p) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {  // keep the raw sequence; names never need it decoded
            out->push_back('\\');
            out->push_back('u');
            break;
          }
          default: return fail("bad escape");
        }
        ++p;
      } else {
        out->push_back(*p++);
      }
    }
    if (p >= end) return fail("unterminated string");
    ++p;
    return true;
  }
  bool integer(int64_t* out) {
    ws();
    bool neg = false;
    if (p < end && *p == '-') { neg = true; ++p; }
    if (p >= end || *p < '0' || *p > '9') return fail("expected integer");
    int64_t v = 0;
    while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
    *out = neg ? -v : v;
    return true;
  }
  bool int_array(std::vector<int64_t>* out) {
    out->clear();
    if (!expect('[')) return false;
    if (peek(']')) { ++p; return true; }
    for (;;) {
      int64_t v;
      if (!integer(&v)) return false;
      out->push_back(v);
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      return expect(']');
    }
  }
  // skip a {str: str} object (metadata)
  bool skip_string_object() {
    if (!expect('{')) return false;
    if (peek('}')) { ++p; return true; }
    for (;;) {
      std::string k, v;
      if (!string(&k) || !expect(':') || !string(&v)) return false;
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      return expect('}');
    }
  }
  bool tensor_entry(TensorMeta* t) {
    if (!expect('{')) return false;
    for (;;) {
      std::string key;
      if (!string(&key) || !expect(':')) return false;
      if (key == "dtype") {
        if (!string(&t->dtype)) return false;
      } else if (key == "shape") {
        if (!int_array(&t->shape)) return false;
      } else if (key == "data_offsets") {
        std::vector<int64_t> off;
        if (!int_array(&off) || off.size() != 2) return fail("bad offsets");
        t->begin = off[0];
        t->end = off[1];
      } else {
        return fail("unknown tensor key");
      }
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      return expect('}');
    }
  }
  bool header(std::vector<TensorMeta>* out) {
    if (!expect('{')) return false;
    if (peek('}')) { ++p; return true; }
    for (;;) {
      std::string name;
      if (!string(&name) || !expect(':')) return false;
      if (name == "__metadata__") {
        if (!skip_string_object()) return false;
      } else {
        TensorMeta t;
        t.name = std::move(name);
        if (!tensor_entry(&t)) return false;
        out->push_back(std::move(t));
      }
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      return expect('}');
    }
  }
};

struct StFile {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  int64_t data_offset = 0;
  std::vector<TensorMeta> tensors;
};

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    std::snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

}  // namespace

extern "C" {

void* cake_st_open(const char* path, char* err, int errlen) {
  StFile* f = new StFile();
  f->fd = ::open(path, O_RDONLY);
  if (f->fd < 0) {
    set_err(err, errlen, std::string("open failed: ") + path);
    delete f;
    return nullptr;
  }
  struct stat st;
  if (fstat(f->fd, &st) != 0 || st.st_size < 8) {
    set_err(err, errlen, "stat failed or file too small");
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  f->map_len = static_cast<size_t>(st.st_size);
  void* m = mmap(nullptr, f->map_len, PROT_READ, MAP_PRIVATE, f->fd, 0);
  if (m == MAP_FAILED) {
    set_err(err, errlen, "mmap failed");
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  f->map = static_cast<const uint8_t*>(m);

  uint64_t hlen = 0;
  std::memcpy(&hlen, f->map, 8);  // little-endian host assumed (x86/arm LE)
  if (8 + hlen > f->map_len) {
    set_err(err, errlen, "header length out of bounds");
    munmap(const_cast<uint8_t*>(f->map), f->map_len);
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  f->data_offset = static_cast<int64_t>(8 + hlen);

  Parser parser{reinterpret_cast<const char*>(f->map + 8),
                reinterpret_cast<const char*>(f->map + 8 + hlen)};
  if (!parser.header(&f->tensors)) {
    set_err(err, errlen, "header parse error: " + parser.err);
    munmap(const_cast<uint8_t*>(f->map), f->map_len);
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  // bounds-check every tensor against the data section
  int64_t data_len = static_cast<int64_t>(f->map_len) - f->data_offset;
  for (const TensorMeta& t : f->tensors) {
    if (t.begin < 0 || t.end < t.begin || t.end > data_len) {
      set_err(err, errlen, "tensor offsets out of bounds: " + t.name);
      munmap(const_cast<uint8_t*>(f->map), f->map_len);
      ::close(f->fd);
      delete f;
      return nullptr;
    }
  }
  madvise(const_cast<uint8_t*>(f->map), f->map_len, MADV_SEQUENTIAL);
  return f;
}

int64_t cake_st_num_tensors(void* h) {
  return static_cast<int64_t>(static_cast<StFile*>(h)->tensors.size());
}

const char* cake_st_name(void* h, int64_t i) {
  return static_cast<StFile*>(h)->tensors[i].name.c_str();
}

const char* cake_st_dtype(void* h, int64_t i) {
  return static_cast<StFile*>(h)->tensors[i].dtype.c_str();
}

int32_t cake_st_ndim(void* h, int64_t i) {
  return static_cast<int32_t>(
      static_cast<StFile*>(h)->tensors[i].shape.size());
}

void cake_st_shape(void* h, int64_t i, int64_t* out) {
  const auto& shape = static_cast<StFile*>(h)->tensors[i].shape;
  for (size_t d = 0; d < shape.size(); ++d) out[d] = shape[d];
}

const uint8_t* cake_st_data(void* h, int64_t i, int64_t* nbytes) {
  StFile* f = static_cast<StFile*>(h);
  const TensorMeta& t = f->tensors[i];
  if (nbytes) *nbytes = t.end - t.begin;
  return f->map + f->data_offset + t.begin;
}

void cake_st_prefetch(void* h, int64_t i) {
  StFile* f = static_cast<StFile*>(h);
  const TensorMeta& t = f->tensors[i];
  const uint8_t* base = f->map + f->data_offset + t.begin;
  size_t len = static_cast<size_t>(t.end - t.begin);
  // align down to page for madvise
  uintptr_t addr = reinterpret_cast<uintptr_t>(base);
  uintptr_t page = addr & ~static_cast<uintptr_t>(4095);
  madvise(reinterpret_cast<void*>(page), len + (addr - page), MADV_WILLNEED);
}

void cake_st_close(void* h) {
  StFile* f = static_cast<StFile*>(h);
  if (f->map) munmap(const_cast<uint8_t*>(f->map), f->map_len);
  if (f->fd >= 0) ::close(f->fd);
  delete f;
}

}  // extern "C"
