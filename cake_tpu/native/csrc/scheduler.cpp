// Continuous-batching request scheduler with a C ABI (ctypes-consumed).
//
// The reference serves one request at a time: the REST handler takes a
// write lock on the whole Master for the duration of a generation
// (cake-core/src/cake/api/text.rs:67 — SURVEY.md §3.3). This scheduler
// replaces that global lock with slot-based continuous batching: requests
// queue FCFS, get admitted to free decode slots, and each engine
// iteration asks for a plan (who needs prefill, who decodes). Token
// reports retire requests on EOS / max-tokens and free their slot for the
// next queued request — admission happens between decode steps, not
// between requests.
//
// Thread-safe: the HTTP threads submit/cancel while the engine thread
// plans/reports. All state behind one mutex; calls are O(slots).

#include <cstdint>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
  uint64_t id;
  int32_t prompt_len;
  int32_t max_new_tokens;
  int32_t generated = 0;
  int32_t slot = -1;
  bool prefilled = false;
};

struct Sched {
  std::mutex mu;
  int32_t max_slots;
  int32_t max_queue;
  std::deque<uint64_t> queue;                   // waiting request ids
  std::unordered_map<uint64_t, Request> reqs;   // queued + active
  std::vector<uint64_t> slots;                  // slot -> req id (0 = free)
  int32_t active = 0;
  uint64_t completed = 0;

  explicit Sched(int32_t ns, int32_t nq) : max_slots(ns), max_queue(nq) {
    slots.assign(static_cast<size_t>(ns), 0);
  }
};

}  // namespace

extern "C" {

void* cake_sched_create(int32_t max_slots, int32_t max_queue) {
  if (max_slots <= 0 || max_queue < 0) return nullptr;
  return new Sched(max_slots, max_queue);
}

void cake_sched_destroy(void* h) { delete static_cast<Sched*>(h); }

// 0 = queued, -1 = queue full, -2 = duplicate/invalid id (0 is reserved)
int32_t cake_sched_submit(void* h, uint64_t id, int32_t prompt_len,
                          int32_t max_new_tokens) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (id == 0 || s->reqs.count(id)) return -2;
  if (static_cast<int32_t>(s->queue.size()) >= s->max_queue) return -1;
  Request r;
  r.id = id;
  r.prompt_len = prompt_len;
  r.max_new_tokens = max_new_tokens;
  s->reqs.emplace(id, r);
  s->queue.push_back(id);
  return 0;
}

// 0 = cancelled, -1 = unknown id
int32_t cake_sched_cancel(void* h, uint64_t id) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->reqs.find(id);
  if (it == s->reqs.end()) return -1;
  if (it->second.slot >= 0) {
    s->slots[static_cast<size_t>(it->second.slot)] = 0;
    --s->active;
  } else {
    for (auto q = s->queue.begin(); q != s->queue.end(); ++q) {
      if (*q == id) { s->queue.erase(q); break; }
    }
  }
  s->reqs.erase(it);
  return 0;
}

// Admit queued requests into free slots, then report the iteration plan.
// prefill_*: requests admitted this call (need their prompt run);
// decode_*: requests already prefilled (need one decode step).
// Arrays must hold >= max_slots entries. Returns total active.
int32_t cake_sched_plan(void* h, uint64_t* prefill_ids,
                        int32_t* prefill_slots, int32_t* n_prefill,
                        uint64_t* decode_ids, int32_t* decode_slots,
                        int32_t* n_decode) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  *n_prefill = 0;
  *n_decode = 0;
  // admission: FCFS into free slots
  for (int32_t slot = 0;
       slot < s->max_slots && !s->queue.empty(); ++slot) {
    if (s->slots[static_cast<size_t>(slot)] != 0) continue;
    uint64_t id = s->queue.front();
    s->queue.pop_front();
    Request& r = s->reqs[id];
    r.slot = slot;
    s->slots[static_cast<size_t>(slot)] = id;
    ++s->active;
    prefill_ids[*n_prefill] = id;
    prefill_slots[*n_prefill] = slot;
    ++(*n_prefill);
  }
  for (int32_t slot = 0; slot < s->max_slots; ++slot) {
    uint64_t id = s->slots[static_cast<size_t>(slot)];
    if (id == 0) continue;
    Request& r = s->reqs[id];
    if (r.prefilled) {
      decode_ids[*n_decode] = id;
      decode_slots[*n_decode] = slot;
      ++(*n_decode);
    }
    r.prefilled = true;  // after this plan, the engine has run its prefill
  }
  return s->active;
}

// Report n_tokens generated for a request; eos != 0 marks end-of-stream.
// Returns 1 if the request finished (slot freed), 0 if still active,
// -1 unknown id.
int32_t cake_sched_report(void* h, uint64_t id, int32_t n_tokens,
                          int32_t eos) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->reqs.find(id);
  if (it == s->reqs.end() || it->second.slot < 0) return -1;
  Request& r = it->second;
  r.generated += n_tokens;
  if (eos || r.generated >= r.max_new_tokens) {
    s->slots[static_cast<size_t>(r.slot)] = 0;
    --s->active;
    ++s->completed;
    s->reqs.erase(it);
    return 1;
  }
  return 0;
}

int32_t cake_sched_queue_depth(void* h) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return static_cast<int32_t>(s->queue.size());
}

int32_t cake_sched_active(void* h) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->active;
}

uint64_t cake_sched_completed(void* h) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->completed;
}

}  // extern "C"
