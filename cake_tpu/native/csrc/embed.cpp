// C-ABI embedding library: host a cake-tpu node inside any C/C++/Swift
// application — the TPU-native analog of the reference's uniffi bindings
// (cake-ios/src/lib.rs:20-87), which expose start_worker() to the iOS app.
//
// The C layer is marshalling only; all behavior lives in
// cake_tpu/native/embed.py. Works both in a fresh host process
// (Py_InitializeEx) and inside an already-running interpreter
// (PyGILState_Ensure on the existing runtime), so the same .so is usable
// from a C main() and from ctypes-based tests.
//
// Exports (string-returning calls: 0 = success, >0 = buffer too small and
// the value is the capacity needed, <0 = failure — see cake_tpu_last_error):
//   cake_tpu_version(buf, cap)
//   cake_tpu_generate(model_dir, prompt, n, buf, cap)
//   cake_tpu_start_worker(name, model, topo, type, address) -> blocking loop
//   cake_tpu_last_error(buf, cap)

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>

namespace {

std::mutex g_err_mu;
std::string g_last_error;

void set_error(const std::string &msg) {
  std::lock_guard<std::mutex> lock(g_err_mu);
  g_last_error = msg;
}

void capture_py_error() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  set_error(msg);
}

// Initialise the interpreter if this process doesn't have one yet.
// call_once: concurrent first calls from a multithreaded host must not race
// Py_InitializeEx.
std::once_flag g_py_once;

void ensure_python() {
  std::call_once(g_py_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);  // no signal handlers: the host app owns signals
      // Release the GIL acquired by initialisation so PyGILState_Ensure
      // below works uniformly for embedded and in-process callers.
      PyEval_SaveThread();
    }
  });
}

// 0 = full copy; >0 = truncated, value is the capacity needed (snprintf
// convention); -2 = unusable buffer. Truncation cuts at a UTF-8 boundary.
long copy_out(const std::string &s, char *buf, long cap) {
  if (buf == nullptr || cap <= 0) return -2;
  size_t n = s.size();
  bool truncated = n > static_cast<size_t>(cap) - 1;
  if (truncated) {
    n = static_cast<size_t>(cap) - 1;
    // don't split a multi-byte sequence: back off over continuation bytes
    while (n > 0 && (static_cast<unsigned char>(s[n]) & 0xC0) == 0x80) --n;
  }
  std::memcpy(buf, s.data(), n);
  buf[n] = '\0';
  return truncated ? static_cast<long>(s.size()) + 1 : 0;
}

// Call cake_tpu.native.embed.<fn>(*args); returns the result or nullptr
// (error captured). Caller holds the GIL and owns the returned reference.
PyObject *call_embed(const char *fn, PyObject *args_tuple) {
  PyObject *mod = PyImport_ImportModule("cake_tpu.native.embed");
  if (mod == nullptr) {
    capture_py_error();
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) {
    capture_py_error();
    return nullptr;
  }
  PyObject *res = PyObject_CallObject(f, args_tuple);
  Py_DECREF(f);
  if (res == nullptr) capture_py_error();
  return res;
}

}  // namespace

extern "C" {

long cake_tpu_last_error(char *buf, long cap) {
  std::lock_guard<std::mutex> lock(g_err_mu);
  return copy_out(g_last_error, buf, cap);
}

long cake_tpu_version(char *buf, long cap) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  long rc = -1;
  PyObject *res = call_embed("version", nullptr);
  if (res != nullptr) {
    const char *c = PyUnicode_AsUTF8(res);
    if (c != nullptr) {
      rc = copy_out(c, buf, cap);
    } else {
      capture_py_error();
    }
    Py_DECREF(res);
  }
  PyGILState_Release(gil);
  return rc;
}

long cake_tpu_generate(const char *model_dir, const char *prompt,
                       int sample_len, char *buf, long cap) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  long rc = -1;
  PyObject *args = Py_BuildValue("(ssi)", model_dir, prompt, sample_len);
  if (args != nullptr) {
    PyObject *res = call_embed("generate", args);
    Py_DECREF(args);
    if (res != nullptr) {
      const char *c = PyUnicode_AsUTF8(res);
      if (c != nullptr) {
        rc = copy_out(c, buf, cap);
      } else {
        capture_py_error();
      }
      Py_DECREF(res);
    }
  } else {
    capture_py_error();
  }
  PyGILState_Release(gil);
  return rc;
}

int cake_tpu_start_worker(const char *name, const char *model_path,
                          const char *topology_path,
                          const char *model_type,
                          const char *address /* nullable */) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject *args = Py_BuildValue(
      "(sssss)", name ? name : "worker", model_path ? model_path : "",
      topology_path ? topology_path : "",
      model_type ? model_type : "text",
      address ? address : "127.0.0.1:10128");
  if (args != nullptr) {
    PyObject *res = call_embed("start_worker", args);
    Py_DECREF(args);
    if (res != nullptr) {
      if (PyLong_Check(res)) {
        rc = static_cast<int>(PyLong_AsLong(res));
      } else {
        set_error("start_worker returned a non-int");
      }
      Py_DECREF(res);
    }
  } else {
    capture_py_error();
  }
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"
