"""Continuous-batching scheduler: native (C++) with a Python fallback.

Replaces the reference's single-tenant global write lock per request
(api/text.rs:67, SURVEY.md §3.3): requests queue FCFS, are admitted into
decode slots between engine iterations, and retire on EOS/max-tokens.

Both implementations expose the same interface:
    submit(id, prompt_len, max_new_tokens) -> bool
    cancel(id) -> bool
    plan() -> (prefill [(id, slot)], decode [(id, slot)])
    report(id, n_tokens, eos) -> bool finished
    queue_depth / active / completed properties

This module is the PRIORITY-FREE fallback: `cake_tpu/sched` wraps this
seam with priority-class queues, anti-starvation aging, preemption and
load shedding (--priority-classes); with those off, the engine drives
these FIFO schedulers unchanged.
"""

from __future__ import annotations

import ctypes
import threading
from collections import deque
from typing import Dict, List, Tuple

from cake_tpu.native import get_library


class PyScheduler:
    """Pure-Python reference implementation (and toolchain-free fallback)."""

    # cakelint lock discipline: the scheduler/shed/slo `_mu` leaf lock
    # nests strictly inside the engine's locks (the engine calls
    # scheduler methods while holding _switch_lock/_rid_lock, never the
    # reverse), and nothing may block under it — it sits on every
    # submit AND every engine iteration
    LOCK_ORDER = ("_switch_lock", "_rid_lock", "_ckpt_lock", "_mu")
    NO_BLOCKING_UNDER = ("_rid_lock", "_mu")

    def __init__(self, max_slots: int, max_queue: int = 1024):
        if max_slots <= 0:
            raise ValueError("max_slots must be positive")
        self.max_slots = max_slots
        self.max_queue = max_queue
        self._mu = threading.Lock()
        self._queue: deque = deque()
        self._reqs: Dict[int, dict] = {}
        self._slots: List[int] = [0] * max_slots
        self._active = 0
        self._completed = 0

    def submit(self, rid: int, prompt_len: int, max_new_tokens: int) -> bool:
        with self._mu:
            if rid == 0 or rid in self._reqs:
                return False
            if len(self._queue) >= self.max_queue:
                return False
            self._reqs[rid] = dict(prompt_len=prompt_len,
                                   max_new=max_new_tokens, generated=0,
                                   slot=-1, prefilled=False)
            self._queue.append(rid)
            return True

    def cancel(self, rid: int) -> bool:
        with self._mu:
            r = self._reqs.pop(rid, None)
            if r is None:
                return False
            if r["slot"] >= 0:
                self._slots[r["slot"]] = 0
                self._active -= 1
            else:
                try:
                    self._queue.remove(rid)
                except ValueError:
                    pass
            return True

    def plan(self) -> Tuple[List[Tuple[int, int]], List[Tuple[int, int]]]:
        with self._mu:
            prefill, decode = [], []
            for slot in range(self.max_slots):
                if not self._queue:
                    break
                if self._slots[slot] != 0:
                    continue
                rid = self._queue.popleft()
                r = self._reqs[rid]
                r["slot"] = slot
                self._slots[slot] = rid
                self._active += 1
                prefill.append((rid, slot))
            for slot in range(self.max_slots):
                rid = self._slots[slot]
                if rid == 0:
                    continue
                r = self._reqs[rid]
                if r["prefilled"]:
                    decode.append((rid, slot))
                r["prefilled"] = True
            return prefill, decode

    def report(self, rid: int, n_tokens: int, eos: bool) -> bool:
        with self._mu:
            r = self._reqs.get(rid)
            if r is None or r["slot"] < 0:
                return False
            r["generated"] += n_tokens
            if eos or r["generated"] >= r["max_new"]:
                self._slots[r["slot"]] = 0
                self._active -= 1
                self._completed += 1
                del self._reqs[rid]
                return True
            return False

    @property
    def queue_depth(self) -> int:
        with self._mu:
            return len(self._queue)

    @property
    def active(self) -> int:
        with self._mu:
            return self._active

    @property
    def completed(self) -> int:
        with self._mu:
            return self._completed


class NativeScheduler:
    """ctypes wrapper over csrc/scheduler.cpp."""

    def __init__(self, max_slots: int, max_queue: int = 1024):
        lib = get_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.max_slots = max_slots
        self._h = lib.cake_sched_create(max_slots, max_queue)
        if not self._h:
            raise ValueError("cake_sched_create failed")
        n = max_slots
        self._pf_ids = (ctypes.c_uint64 * n)()
        self._pf_slots = (ctypes.c_int32 * n)()
        self._dc_ids = (ctypes.c_uint64 * n)()
        self._dc_slots = (ctypes.c_int32 * n)()

    def submit(self, rid: int, prompt_len: int, max_new_tokens: int) -> bool:
        return self._lib.cake_sched_submit(
            self._h, rid, prompt_len, max_new_tokens) == 0

    def cancel(self, rid: int) -> bool:
        return self._lib.cake_sched_cancel(self._h, rid) == 0

    def plan(self):
        n_pf = ctypes.c_int32()
        n_dc = ctypes.c_int32()
        self._lib.cake_sched_plan(
            self._h, self._pf_ids, self._pf_slots, ctypes.byref(n_pf),
            self._dc_ids, self._dc_slots, ctypes.byref(n_dc))
        prefill = [(self._pf_ids[i], self._pf_slots[i])
                   for i in range(n_pf.value)]
        decode = [(self._dc_ids[i], self._dc_slots[i])
                  for i in range(n_dc.value)]
        return prefill, decode

    def report(self, rid: int, n_tokens: int, eos: bool) -> bool:
        return self._lib.cake_sched_report(
            self._h, rid, n_tokens, 1 if eos else 0) == 1

    @property
    def queue_depth(self) -> int:
        return self._lib.cake_sched_queue_depth(self._h)

    @property
    def active(self) -> int:
        return self._lib.cake_sched_active(self._h)

    @property
    def completed(self) -> int:
        return self._lib.cake_sched_completed(self._h)

    def __del__(self):
        try:
            if self._h:
                self._lib.cake_sched_destroy(self._h)
                self._h = None
        except Exception:
            pass


def make_scheduler(max_slots: int, max_queue: int = 1024):
    """Native scheduler when the toolchain allows, else the Python one."""
    if get_library() is not None:
        return NativeScheduler(max_slots, max_queue)
    return PyScheduler(max_slots, max_queue)
