"""Pythonic wrapper over the native safetensors reader, with fallback.

`read_file(path)` returns {name: np.ndarray} where arrays are zero-copy
views into the native mmap (or, in fallback mode, into a numpy memmap —
same semantics, reference utils/mod.rs:100-103). The returned `StFile`
keeps the mapping alive; hold it for as long as the arrays are in use.
"""

from __future__ import annotations

import ctypes
from typing import Dict, Iterable, Optional

import numpy as np

from cake_tpu.native import get_library
from cake_tpu.utils.loading import _ST_DTYPES


class _MmapView(np.ndarray):
    """ndarray view that keeps the owning StFile alive via an attribute.

    Any derived view (reshape, astype-view, slice) chains to this instance
    through .base, so the mapping cannot be unmapped while data is
    reachable."""
    _keepalive = None


class StFile:
    """An open (native) safetensors file; tensors are zero-copy views."""

    def __init__(self, path: str):
        lib = get_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        err = ctypes.create_string_buffer(512)
        self._h = lib.cake_st_open(path.encode(), err, len(err))
        if not self._h:
            raise OSError(f"cake_st_open({path!r}): "
                          f"{err.value.decode(errors='replace')}")
        self.path = path

    def names(self):
        n = self._lib.cake_st_num_tensors(self._h)
        return [self._lib.cake_st_name(self._h, i).decode()
                for i in range(n)]

    def _tensor(self, i: int, prefetch: bool = True) -> np.ndarray:
        lib, h = self._lib, self._h
        dtype = _ST_DTYPES[lib.cake_st_dtype(h, i).decode()]
        ndim = lib.cake_st_ndim(h, i)
        shape_buf = (ctypes.c_int64 * max(ndim, 1))()
        lib.cake_st_shape(h, i, shape_buf)
        shape = tuple(shape_buf[d] for d in range(ndim))
        nbytes = ctypes.c_int64()
        ptr = lib.cake_st_data(h, i, ctypes.byref(nbytes))
        if prefetch:
            lib.cake_st_prefetch(h, i)
        buf = (ctypes.c_uint8 * nbytes.value).from_address(
            ctypes.addressof(ptr.contents))
        arr = np.frombuffer(buf, dtype=dtype).view(_MmapView)
        arr._keepalive = self
        arr = arr.reshape(shape)
        arr.flags.writeable = False
        return arr

    def tensors(self, names: Optional[Iterable[str]] = None,
                prefetch: bool = True) -> Dict[str, np.ndarray]:
        wanted = set(names) if names is not None else None
        out = {}
        n = self._lib.cake_st_num_tensors(self._h)
        for i in range(n):
            name = self._lib.cake_st_name(self._h, i).decode()
            if wanted is not None and name not in wanted:
                continue
            out[name] = self._tensor(i, prefetch=prefetch)
        return out

    def close(self):
        """Unmap the file. Only call once every returned view is dead —
        views hold a reference to this object (so plain GC is always safe),
        but an explicit close() while views live would leave them dangling.
        """
        if self._h:
            self._lib.cake_st_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def read_file(path: str, names: Optional[Iterable[str]] = None,
              prefetch: bool = True):
    """(tensors dict, file handle or None). The arrays keep the mapping
    alive on their own (base chain), so the handle is informational; do not
    close() it while arrays are in use. Falls back to the pure-Python
    memmap reader when the native library is unavailable."""
    if get_library() is not None:
        f = StFile(path)
        return f.tensors(names, prefetch=prefetch), f
    from cake_tpu.utils.loading import _st_load_file
    return _st_load_file(path, names), None
