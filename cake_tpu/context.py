"""Context: process-wide shared state built once from Args.

Capability parity with the reference `Context` (cake-core/src/cake/mod.rs:39-100):
parsed args, dtype policy, topology, device, model config, weight source.
On TPU it additionally owns the mesh and sharding plan (parallel/).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from cake_tpu.args import Args, SDArgs
from cake_tpu.topology import Topology
from cake_tpu.utils.devices import get_inference_device, resolve_dtype

log = logging.getLogger(__name__)


def _resolve_flash(args: Args) -> bool:
    """--flash-attention / --no-flash-attention; default on iff real TPU."""
    if args.flash_attention is not None:
        return args.flash_attention
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@dataclass
class Context:
    args: Args
    sd_args: Optional[SDArgs]
    dtype: object
    device: object
    topology: Optional[Topology] = None
    llama_config: Optional[object] = None

    @classmethod
    def from_args(cls, args: Args, sd_args: Optional[SDArgs] = None) -> "Context":
        dtype = resolve_dtype(args.dtype)
        device = get_inference_device(cpu=args.cpu, device_idx=args.device_idx)
        topology = Topology.from_path(args.topology) if args.topology else None

        llama_config = None
        if args.model_type.value == "text" and args.model:
            import dataclasses

            from cake_tpu.models.llama.config import load_config
            cfg_path = os.path.join(args.model, "config.json")
            if os.path.exists(cfg_path):
                llama_config = dataclasses.replace(
                    load_config(args.model),
                    use_flash_attention=_resolve_flash(args),
                )

        log.info("context: device=%s dtype=%s topology=%s",
                 device, args.dtype,
                 list(topology.keys()) if topology else None)
        return cls(args=args, sd_args=sd_args, dtype=dtype, device=device,
                   topology=topology, llama_config=llama_config)

    # -- model loading -------------------------------------------------------

    def load_text_model(self):
        """Build a LlamaGenerator; with a multi-stage topology (or tp/dp > 1)
        the params/cache are placed on a ("dp","stage","tp") mesh per the
        ParallelPlan and the generator drives the pipelined forward — the
        reference's topology-driven serving (topology.rs:43-91 feeding
        llama.rs:203-220), as one SPMD program instead of TCP hops."""
        from cake_tpu.models.llama.config import LlamaConfig
        from cake_tpu.models.llama.generator import (
            ByteTokenizer, LlamaGenerator, load_tokenizer,
        )
        from cake_tpu.ops.sampling import SamplingConfig

        import dataclasses

        a = self.args
        cfg = self.llama_config or dataclasses.replace(
            LlamaConfig.tiny(), use_flash_attention=_resolve_flash(a)
        )
        if a.model and os.path.exists(os.path.join(a.model, "tokenizer.json")):
            tokenizer = load_tokenizer(a.model)
        else:
            tokenizer = ByteTokenizer(cfg.vocab_size)

        from cake_tpu.models import load_text_params
        from cake_tpu.parallel.plan import ParallelPlan
        from cake_tpu.utils.loading import has_weights
        plan = ParallelPlan.from_topology(cfg, self.topology, args=a)

        # stage-local streaming load (reference worker.rs:106-127 parity,
        # per shard): with a sharded placement and real weights on disk,
        # every tensor lands directly on its mesh shard — no full-model
        # host/device copy ever exists, which is what lets a 70B (or
        # Mixtral-8x22B) topology actually load instead of dying at the
        # eager full-tree load.
        stream_sharded = (
            (plan.stages > 1 or plan.tp > 1 or plan.dp > 1)
            and (a.sp <= 1 or plan.stages > 1) and has_weights(a.model)
        )
        if stream_sharded:
            params = None   # loaded inside the topology branch, post-mesh
        else:
            params = load_text_params(cfg, a.model, self.dtype)
            params = self._maybe_quantize(params)

        # --repeat-penalty unset -> reference default 1.1 (llama.rs:311);
        # speculative mode resolves unset to 1.0 instead (parallel verify
        # has no penalty-ring replay) while honoring explicit values
        penalty = a.repeat_penalty
        if penalty is None:
            penalty = 1.0 if a.draft_model is not None else 1.1
        sampling = SamplingConfig(
            temperature=a.temperature, top_k=a.top_k, top_p=a.top_p,
            repeat_penalty=penalty, repeat_last_n=a.repeat_last_n,
        )
        max_seq = min(a.max_seq_len, cfg.max_position_embeddings)
        from cake_tpu.utils.devices import resolve_kv_dtype
        if a.kv_dtype == "int8":
            # int8 KV is the PAGED ENGINE's quantized pool (cake_tpu/kv;
            # master.make_engine passes --kv-dtype through): the
            # sequential generator's dense cache keeps the compute
            # dtype — scales are per page, and the dense cache has none
            kv_dtype = self.dtype
        else:
            kv_dtype = (resolve_kv_dtype(a.kv_dtype) if a.kv_dtype
                        else self.dtype)

        kwargs = {}
        if a.sp > 1:
            # sequence/context parallelism: ring-attention prefill +
            # merged-stats decode over an ("sp",) / ("sp","tp") /
            # ("stage","sp"[,"tp"]) mesh — the long-context serving mode
            # (prompt sharded over chips, optionally with Megatron head
            # sharding within each shard and/or layer ranges over stages
            # for models too big for one chip's HBM)
            if plan.dp > 1 and plan.stages > 1:
                raise ValueError(
                    "--sp composes with --dp OR topology stages, not "
                    "both in one mesh")
            if plan.dp > 1 and a.batch_size % plan.dp != 0:
                raise ValueError(
                    f"--batch-size {a.batch_size} must be divisible by "
                    f"--dp {plan.dp}")
            if plan.tp > 1 and a.quant == "int4":
                # int4 group-wise weights CAN shard their contract dim
                # over tp (wo/w_down are contract-sharded Megatron-style)
                # as long as every tp shard holds whole groups — the
                # packed nibble layout and the per-group scales are then
                # self-contained per shard (ops/quant.expand_spec already
                # gives the scale's group dim the q spec). Misaligned
                # dims would split a group across devices, so reject
                # exactly those.
                from cake_tpu.ops.quant import pick_group
                for name, dim in (
                        ("wo", cfg.num_attention_heads * cfg.head_dim),
                        ("w_down", cfg.intermediate_size)):
                    g = pick_group(dim)
                    if (dim // g) % plan.tp:
                        raise ValueError(
                            f"--sp with --tp {plan.tp} and --quant int4: "
                            f"{name}'s contract dim {dim} has {dim // g} "
                            f"groups of {g}, not divisible over tp — a "
                            "tp shard would split a quantization group. "
                            "Use int8, drop --tp, or pick a tp that "
                            "divides the group count")
            if cfg.sliding_window is not None:
                raise ValueError(
                    "--sp (ring attention) does not implement "
                    "sliding-window attention; serve this model without "
                    "--sp")
            import numpy as np
            from jax.sharding import Mesh

            from cake_tpu.parallel.context_parallel import SPGeneratorForward
            devices = jax.devices()
            tp = plan.tp
            dp = plan.dp
            stages = plan.stages
            need = stages * dp * a.sp * tp
            if need > len(devices):
                raise ValueError(
                    f"stages {stages} x --dp {dp} x --sp {a.sp} x --tp "
                    f"{tp} needs {need} devices, have {len(devices)}")
            if jax.process_count() > 1 and need != len(devices):
                # multi-host: a mesh over a device subset could land
                # entirely on one process; the other processes would
                # replay programs with no addressable shards. Spanning
                # ALL global devices keeps every process a participant.
                raise ValueError(
                    f"multi-host --sp meshes must span every device: "
                    f"sp x tp (x dp/stages) = {need} != "
                    f"{len(devices)} global devices")
            if tp > 1 and cfg.num_key_value_heads % tp != 0:
                raise ValueError(
                    f"--tp {tp} must divide kv heads "
                    f"{cfg.num_key_value_heads}")
            if stages > 1 and cfg.num_hidden_layers % stages != 0:
                raise ValueError(
                    f"topology stages {stages} must divide layer count "
                    f"{cfg.num_hidden_layers}")
            # split the window: context (sharded) + decode tail (replicated);
            # the tail MUST hold sample_len generated tokens — a too-small
            # tail would clamp cache writes over live entries
            tail = max(a.sample_len, 16)
            ctx_len = ((max_seq - tail) // a.sp) * a.sp
            if ctx_len <= 0:
                raise ValueError(
                    f"--max-seq-len {max_seq} leaves no context window for "
                    f"sp={a.sp} after a {tail}-token decode tail; raise "
                    "--max-seq-len or lower --sample-len")
            if stages > 1:
                axes = (["stage", "sp"] + (["tp"] if tp > 1 else []))
                mesh = Mesh(
                    np.array(devices[:need]).reshape(
                        *(stages, a.sp) + ((tp,) if tp > 1 else ())),
                    tuple(axes))
                from cake_tpu.parallel.sp_pipeline import (
                    place_sp_stage_params,
                )
                if params is None:   # streaming stage-local load
                    params = self._load_params_streamed(cfg, mesh, tp > 1)
                    params = self._maybe_quantize(params)
                params = place_sp_stage_params(mesh, cfg, params,
                                               tp=tp > 1)
            elif dp > 1 or tp > 1:
                # ("dp",)? x "sp" x ("tp",)? — batch over dp groups, each
                # running its own sp ring (collectives name "sp"/"tp"
                # only, so shard_map scopes them per group)
                shape = (((dp,) if dp > 1 else ())
                         + (a.sp,) + ((tp,) if tp > 1 else ()))
                axes = ((("dp",) if dp > 1 else ())
                        + ("sp",) + (("tp",) if tp > 1 else ()))
                mesh = Mesh(np.array(devices[:need]).reshape(shape), axes)
                if tp > 1:
                    # place the block params on their tp shards up front
                    # so every sp call doesn't pay a reshard
                    from cake_tpu.parallel.context_parallel import (
                        place_sp_params,
                    )
                    params = place_sp_params(mesh, cfg, params, tp=True)
            else:
                mesh = Mesh(np.array(devices[:a.sp]), ("sp",))
            fwd = SPGeneratorForward(
                mesh, cfg, ctx_len, max_seq - ctx_len, kv_dtype=kv_dtype,
                tp=tp > 1, params=params, stages=stages, dp=dp > 1)
            # placeholder cache: the SP prefill allocates its own sharded
            # SPCache; the generator's default dense [L,B,max_seq,...]
            # buffer would be dead weight at exactly the context lengths
            # SP exists for
            from cake_tpu.models.llama.cache import KVCache
            kwargs = dict(forward_fn=fwd,
                          cache=KVCache.create(cfg, a.batch_size, 1,
                                               dtype=kv_dtype))
            log.info("sp serving: ring prefill over sp=%d%s, ctx=%d "
                     "tail=%d", a.sp,
                     f" x stages={stages}" if stages > 1 else "",
                     ctx_len, max_seq - ctx_len)
        elif plan.stages > 1 or plan.tp > 1 or plan.dp > 1:
            from cake_tpu.parallel.pipeline import (
                make_pipeline_forward, place_for_pipeline,
            )
            if a.batch_size % plan.dp != 0:
                raise ValueError(
                    f"--batch-size {a.batch_size} must be divisible by "
                    f"--dp {plan.dp}")
            if (a.batch_size // plan.dp) % a.microbatches != 0:
                raise ValueError(
                    f"per-replica batch {a.batch_size // plan.dp} must be "
                    f"divisible by --microbatches {a.microbatches} "
                    "(GPipe slices the batch into microbatches)")
            mesh = plan.build_mesh()
            tp, dp = plan.tp > 1, plan.dp > 1
            from cake_tpu.parallel.sharding import create_sharded_cache
            cache = create_sharded_cache(
                cfg, a.batch_size, max_seq, mesh,
                tp_axis="tp" if tp else None,
                dp_axis="dp" if dp else None,
                stage_axis="stage", dtype=kv_dtype,
            )
            if params is None:   # streaming stage-local load (see above)
                params = self._load_params_streamed(cfg, mesh, tp)
                params = self._maybe_quantize(params)
            params, cache = place_for_pipeline(params, cache, mesh,
                                               tp=tp, dp=dp)
            fwd = make_pipeline_forward(
                mesh, cfg,
                num_microbatches=a.microbatches,
                tp=tp, dp=dp, params=params,
            )
            kwargs = dict(forward_fn=fwd, cache=cache,
                          parallel=(plan, mesh))
            log.info("topology-sharded serving:\n%s", plan.describe())

        if a.draft_model is not None:
            if kwargs or a.batch_size != 1:
                raise ValueError(
                    "--draft-model (speculative decoding) is batch-1 "
                    "single-device; it does not compose with "
                    "--sp/--tp/--dp/topology stages")
            if a.prefill_chunk is not None:
                raise ValueError(
                    "--prefill-chunk is not supported with --draft-model "
                    "(speculative prefill is whole-prompt)")
            gen = self._load_speculative(cfg, params, tokenizer, sampling,
                                         max_seq, kv_dtype)
        else:
            gen = LlamaGenerator(
                cfg, params, tokenizer,
                max_seq_len=max_seq,
                batch_size=a.batch_size, sampling=sampling, seed=a.seed,
                cache_dtype=kv_dtype, prefill_chunk=a.prefill_chunk,
                **kwargs,
            )
        from cake_tpu.utils.profiling import log_memory
        log_memory("model loaded")  # reference llama.rs:233-236
        return gen

    def _load_params_streamed(self, cfg, mesh, tp: bool):
        """Stream weights from disk directly onto their pipeline shards
        (models/llama/params.load_params_sharded) — each tensor is read
        once per addressable shard slice and never exists as a full
        host/device array. Quantization (_maybe_quantize) then runs
        shard-wise on the placed tree, so peak per-device HBM is
        ~1.5x one shard, not 1.5x the model."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from cake_tpu.models.llama.params import block_param_keys
        from cake_tpu.parallel.pipeline import pipeline_param_specs

        if cfg.is_moe:
            from cake_tpu.models.moe.params import load_params_sharded
        else:
            from cake_tpu.models.llama.params import load_params_sharded

        specs = pipeline_param_specs(block_param_keys(cfg),
                                     tp_axis="tp" if tp else None)
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        log.info("streaming stage-local weight load from %s",
                 self.args.model)
        return load_params_sharded(self.args.model, cfg, shardings,
                                   dtype=self.dtype)

    def _maybe_quantize(self, params):
        """Apply --quant to a param tree without 1.5x peak HBM: int8
        donates the tree (fp buffers free as quantized copies
        materialise); int4 quantizes leaf-at-a-time (packed outputs can't
        alias donated buffers, so donation would warn and hold fp leaves
        to computation end)."""
        a = self.args
        if a.quant not in ("int8", "int4"):
            return params
        from functools import partial

        from cake_tpu.ops.quant import (
            quantize_params, quantize_params_leafwise,
        )
        if a.quant == "int8":
            params = jax.jit(partial(quantize_params, bits=8),
                             donate_argnums=0)(params)
        else:
            # int4 outputs (packed uint8 + group scales) can never alias
            # a donated fp buffer; the leafwise path frees fp leaves
            # incrementally without unusable-donation warnings
            params = quantize_params_leafwise(params, bits=4)
        log.info("weights quantized to %s (weight-only, %s)", a.quant,
                 "per-channel" if a.quant == "int8" else "group-wise")
        return params

    def _load_speculative(self, cfg, params, tokenizer, sampling, max_seq,
                          kv_dtype):
        import dataclasses

        from cake_tpu.models import load_text_params
        from cake_tpu.models.llama.config import LlamaConfig, load_config
        from cake_tpu.models.llama.speculative import SpeculativeGenerator

        a = self.args
        d_dir = a.draft_model
        if d_dir and os.path.exists(os.path.join(d_dir, "config.json")):
            d_cfg = dataclasses.replace(
                load_config(d_dir), use_flash_attention=_resolve_flash(a))
        else:
            d_cfg = dataclasses.replace(
                LlamaConfig.tiny(), use_flash_attention=_resolve_flash(a))
        if d_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab {d_cfg.vocab_size} != target vocab "
                f"{cfg.vocab_size}: speculation verifies draft token ids "
                "directly, so the models must share a tokenizer")
        d_params = self._maybe_quantize(
            load_text_params(d_cfg, d_dir, self.dtype))
        log.info("speculative serving: gamma=%d draft=%s", a.spec_gamma,
                 d_dir or "<random tiny>")
        return SpeculativeGenerator(
            cfg, params, d_cfg, d_params, tokenizer,
            gamma=a.spec_gamma, max_seq_len=max_seq, sampling=sampling,
            seed=a.seed, cache_dtype=kv_dtype,
            spec_rounds=a.spec_rounds,
        )

    def load_image_model(self):
        from cake_tpu.models.sd.sd import SDGenerator
        gen = SDGenerator.load(self)
        a = self.args
        if a.dp > 1 or jax.process_count() > 1:
            # whole-pipeline SPMD over a ("dp",) mesh: --dp N splits the
            # UNet batch (guidance pair / multi-image) over N devices;
            # under multi-host every process must dispatch, so the mesh
            # spans ALL devices and cli._serve_multihost replays
            # generation ops to the followers
            if self.topology is not None:
                why = ("--dp" if a.dp > 1
                       else "multi-host image serving (which meshes the "
                            "whole pipeline)")
                raise ValueError(
                    f"{why} and an SD component topology are mutually "
                    "exclusive: one SPMD program cannot mix mesh-sharded "
                    "and committed-to-device components")
            import numpy as np
            from jax.sharding import Mesh
            devices = jax.devices()
            if jax.process_count() > 1:
                # multi-host: the mesh MUST span every process (each one
                # dispatches the same SPMD program); a --dp that asks
                # for anything else is an error, not silently ignored
                if a.dp > 1 and a.dp != len(devices):
                    raise ValueError(
                        f"multi-host image serving meshes over ALL "
                        f"{len(devices)} devices; --dp {a.dp} cannot be "
                        "honored (drop the flag or set it to the total "
                        "device count)")
                n = len(devices)
            else:
                n = a.dp
                if n > len(devices):
                    raise ValueError(
                        f"--dp {n} needs {n} devices, have "
                        f"{len(devices)}")
            gen.shard_for_mesh(Mesh(np.array(devices[:n]), ("dp",)))
        return gen
