"""Runtime checkpoint/resume for the serving engine.

The reference has **no** runtime checkpointing (SURVEY.md §5: chat state is
in-memory, a crashed process loses every in-flight generation). This module
adds it for the continuous-batching engine:

  * `snapshot(engine)` captures every queued / in-flight / finished request
    as a JSON-serializable record: prompt ids, tokens generated so far,
    remaining budget, per-request sampling params, plus an engine
    compatibility fingerprint.
  * `save(engine, path)` / `load(path)` persist the snapshot.
  * `resume(engine, snap)` resubmits unfinished requests with
    prompt = original prompt + tokens generated so far — the KV cache is
    rebuilt by re-prefilling the transcript, the standard recovery design
    for serving systems: no device-buffer dump to go stale, works across
    restarts, topology changes, and host counts.

Determinism: greedy (temperature=0) continuations produce exactly the
tokens the uninterrupted run would have produced — including with
repeat_penalty != 1.0: `resume` passes each request's generated tokens as
`prime_penalty_tokens`, so the engine reconstructs the penalty ring at the
resume boundary instead of restarting it empty. Stochastic requests resume
with a fresh RNG key (their continuation is a different but valid sample).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

# The fingerprint's digest definition is part of the version contract: a
# digest-format change MUST bump this, or old snapshots would present as
# weights mismatches instead of an explicit version error.
SNAPSHOT_VERSION = 3


def _params_digest(params) -> str:
    """Cheap but weight-sensitive model identity: sha256 over small
    deterministic samples of the first/last leaves. Shape-only
    fingerprints would let a snapshot resume into a *different* model
    with identical dims and replay token ids against the wrong weights."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    leaves = jax.tree.leaves(params)
    for leaf in leaves[:4] + leaves[-4:]:
        # slice ON DEVICE before the host transfer: device_get of a whole
        # multi-GiB leaf on the SIGTERM save path could overrun the kill
        # grace period
        h.update(str(tuple(leaf.shape)).encode())
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            # mesh spans processes (multi-host serving): sample this
            # process's first shard — deterministic for a fixed topology,
            # and save/restore both run on the coordinator. A topology
            # change surfaces as a fingerprint mismatch (the snapshot is
            # then sidelined), which is the safe direction.
            leaf = leaf.addressable_shards[0].data
        sample = np.asarray(leaf.reshape(-1)[:256])
        h.update(sample.astype(np.float32, copy=False).tobytes())
    return h.hexdigest()[:16]


def _fingerprint(engine) -> Dict:
    # memoized on the engine: weights are immutable during serving, and
    # the digest's D2H sample must NOT run on the failure path — with a
    # dead host mid-mesh the local device stream can be wedged behind
    # the failed collective, stalling exactly the pre-fail snapshot that
    # exists to survive that failure. warm_fingerprint() computes it at
    # startup while the mesh is healthy.
    import copy
    fp = getattr(engine, "_ckpt_fingerprint", None)
    if fp is not None:
        # deep copy: snapshots embed this dict, and a caller mutating a
        # snapshot must not silently edit the cache (which would make a
        # doctored fingerprint compare equal to itself)
        return copy.deepcopy(fp)
    import dataclasses
    c = engine.config
    cfg = (dataclasses.asdict(c) if dataclasses.is_dataclass(c)
           else {"repr": repr(c)})
    # JSON round-trip normalisation (tuples -> lists) so a saved+loaded
    # fingerprint compares equal to a freshly computed one
    cfg = json.loads(json.dumps(cfg))
    fp = {
        "config": cfg,
        "max_seq_len": engine.max_seq_len,
        # ring width shapes penalty reconstruction; a mismatch silently
        # changes the penalty window, so it is part of compatibility
        "repeat_last_n": engine.defaults.repeat_last_n,
        "params": _params_digest(engine.params),
    }
    engine._ckpt_fingerprint = fp
    return copy.deepcopy(fp)


def warm_fingerprint(engine) -> None:
    """Compute and cache the engine fingerprint now, while the mesh is
    healthy — so a later pre-fail snapshot needs no device work."""
    _fingerprint(engine)


def is_resumable(rec: Dict) -> bool:
    """Whether a snapshot record represents an interrupted generation
    that resume() would resubmit — THE resumability predicate, shared by
    resume(), the pre-fail writer, and the shutdown keep-or-save rule so
    they cannot diverge."""
    return (not rec.get("finished") and not rec.get("error")
            and rec.get("remaining", 0) > 0)


def has_resumable(path: Optional[str]) -> bool:
    """True when `path` holds a checkpoint with resumable records (the
    shutdown save preserves such a file when it was written by the
    pre-fail path — api/server.py save_and_exit)."""
    if not path or not os.path.exists(path):
        return False
    try:
        with open(path) as f:
            snap = json.load(f)
        return any(is_resumable(r) for r in snap.get("requests", []))
    except (OSError, ValueError):
        return False


def snapshot_requests(engine) -> List[Dict]:
    """Capture the request records alone — pure Python, no device work,
    so it is safe and fast even when the mesh is wedged. The engine
    loop's fatal path captures these BEFORE _fail_all empties the
    registry, then writes them after the clients are released
    (engine._snapshot_before_fail(requests=...))."""
    requests: List[Dict] = []
    for rid, req in sorted(dict(engine._requests).items()):
        finished = req.done.is_set()
        requests.append({
            "rid": rid,
            "prompt_ids": list(req.prompt_ids),
            "out_tokens": list(req.out_tokens),
            # full generated-token history incl. pre-resume generations, so
            # a request interrupted twice still reconstructs the penalty
            # ring over its whole transcript, not just the latest leg
            "penalty_context": list(req.prime_tokens) + list(req.out_tokens),
            "remaining": max(0, req.max_new_tokens - len(req.out_tokens)),
            # SLO class survives the restart (older snapshots lack the
            # field; resume defaults it to "standard")
            "priority": getattr(req, "priority", "standard"),
            "temperature": req.temperature,
            "top_p": req.top_p,
            "repeat_penalty": req.repeat_penalty,
            "finished": finished,
            "error": str(req.error) if req.error else None,
            # durable-serving fields (serve/journal.py): the client's
            # idempotency key survives the restart (a retried submit
            # attaches instead of double-admitting), and `replayed`
            # keeps the absolute stream coordinate — tokens generated
            # in PREVIOUS process generations that are folded into
            # prompt_ids already — so SSE event ids stay monotonic
            # across any number of restarts
            "idempotency_key": getattr(req, "idempotency_key", None),
            "replayed": list(getattr(req, "replayed_tokens", ()) or ()),
        })
    return requests


def snapshot(engine, requests: Optional[List[Dict]] = None) -> Dict:
    """Capture engine request state. Call with the engine stopped (or at
    least quiesced): the engine thread mutates request state per step.
    requests: pre-captured snapshot_requests() records (pre-fail path)."""
    snap = {
        "version": SNAPSHOT_VERSION,
        "engine": _fingerprint(engine),
        "requests": (snapshot_requests(engine) if requests is None
                     else requests),
    }
    # informational only — the LIVE effective engine config at snapshot
    # time (cake_tpu/autotune). Deliberately OUTSIDE the fingerprint:
    # the whole point of the fold-tokens-into-prompt resume is that a
    # snapshot restores into a DIFFERENT config (more slots, a paged
    # pool, a post-switch engine) token-identically, so the config must
    # never gate compatibility — it just tells the operator what the
    # requests were being served under (and which autotune epoch).
    cfg_fn = getattr(engine, "current_config", None)
    if cfg_fn is not None:
        try:
            snap["engine_config"] = cfg_fn().to_dict()
            snap["config_epoch"] = getattr(engine, "config_epoch", 0)
        except Exception:  # noqa: BLE001 — metadata, never the save
            log.debug("snapshot: engine config capture failed",
                      exc_info=True)
    # informational too: the sentinel's self-calibrated baselines
    # (obs/sentinel.py BaselineDetectors). A graceful restart adopts
    # them instead of spending calibrate_n windows re-learning — and
    # cannot fire a false step-time regression against an empty
    # baseline meanwhile. Outside the fingerprint for the same reason
    # as engine_config: telemetry state never gates a resume.
    sen = getattr(engine, "sentinel", None)
    if sen is not None:
        try:
            baselines = sen.export_baselines()
            if baselines:
                snap["sentinel_baselines"] = baselines
        except Exception:  # noqa: BLE001 — metadata, never the save
            log.debug("snapshot: sentinel baseline capture failed",
                      exc_info=True)
    return snap


def write(snap: Dict, path: str) -> None:
    """Write a snapshot to `path` (atomic: tmp + fsync + rename). The
    fsync BEFORE the rename is load-bearing: without it a power loss
    can leave the rename durable but the data not — a zero-length or
    torn file under the final name, exactly the torn-JSON startup
    crash this function exists to prevent. A crash at any point leaves
    either the previous good checkpoint or the complete new one. The
    tmp name is thread-unique: a pre-fail snapshot (health-monitor
    thread) and a shutdown save can overlap in one process."""
    import uuid
    tmp = f"{path}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(snap, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        # never leave the tmp litter behind a failed save
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    log.info("checkpoint: %d request(s) -> %s", len(snap["requests"]), path)


def save(engine, path: str) -> Dict:
    """Snapshot the engine and write it to `path` (atomic replace)."""
    snap = snapshot(engine)
    write(snap, path)
    return snap


def load(path: str) -> Optional[Dict]:
    """Load a snapshot. A corrupt or truncated file — the signature of
    a crash mid-write before write() grew its fsync, or disk rot —
    degrades to None ("no checkpoint") with a LOUD warning instead of
    raising: a bad checkpoint must never crash-loop server startup,
    and the atomic writer means the previous good state was already
    lost, so starting empty is the only option anyway. A version
    mismatch still raises (the file is intact — the operator should
    see an explicit version error, and api.start sidelines it)."""
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, ValueError) as e:
        log.warning("checkpoint %s is unreadable/corrupt (%s); starting "
                    "with no checkpoint", path, e)
        return None
    if not isinstance(snap, dict):
        log.warning("checkpoint %s is not a snapshot object; starting "
                    "with no checkpoint", path)
        return None
    if snap.get("version") != SNAPSHOT_VERSION:
        raise ValueError(
            f"unsupported snapshot version {snap.get('version')!r}")
    return snap


def resume(engine, snap: Dict, strict: bool = True) -> Tuple[List, List[Dict]]:
    """Resubmit unfinished snapshot requests into `engine` (started).

    Returns (handles, finished_records): one RequestHandle per resumed
    request, in snapshot order, plus the records of requests that had
    already finished (their transcripts survive the restart).
    strict: fingerprint mismatch raises instead of warning.
    """
    from cake_tpu.obs import metrics as obs_metrics
    fp, want = _fingerprint(engine), snap.get("engine", {})
    if fp != want:
        msg = f"snapshot fingerprint {want} != engine {fp}"
        if strict:
            raise ValueError(msg)
        log.warning("%s (resuming anyway)", msg)

    # adopt persisted sentinel baselines BEFORE resubmitting load: a
    # restored detector must never spend its first windows calibrating
    # on resume-storm traffic (best-effort — telemetry never gates a
    # resume; restore_baselines itself skips calibrated/mismatched
    # detectors and non-positive values)
    sen = getattr(engine, "sentinel", None)
    if sen is not None:
        try:
            sen.restore_baselines(snap.get("sentinel_baselines"))
        except Exception:  # noqa: BLE001
            log.debug("resume: sentinel baseline restore failed",
                      exc_info=True)

    resumed_c = obs_metrics.counter(
        "cake_checkpoint_resumed_requests_total",
        "Snapshot requests resubmitted into a restarted engine")
    dropped_c = obs_metrics.counter(
        "cake_checkpoint_dropped_requests_total",
        "Snapshot requests that could not be resubmitted")
    handles, finished = [], []
    for rec in snap["requests"]:
        try:
            # field reads stay inside the try: one malformed record must
            # not abort the loop after earlier requests were resubmitted
            if not is_resumable(rec):
                finished.append(rec)
                continue
            ids = rec["prompt_ids"] + rec["out_tokens"]
            limit = getattr(engine, "prompt_limit", None)
            if limit is not None and len(ids) > limit:
                # windowed serving modes (the sp engine's ctx+tail
                # layout) re-prefill a resumed request's whole
                # transcript into the prompt window; a transcript past
                # the window has no replay path — documented limitation
                raise ValueError(
                    f"resumed context {len(ids)} exceeds this serving "
                    f"mode's prompt window {limit}")
            budget = getattr(engine, "decode_budget", None)
            truncated = budget is not None and rec["remaining"] > budget
            if truncated:
                # submit() clamps max_new_tokens to the tail capacity;
                # that silently shortens the client's resumed
                # generation, so make it loud and visible on the trace
                log.warning(
                    "resume: rid=%s has %d tokens remaining but this "
                    "serving mode's decode budget is %d; the resumed "
                    "generation will be truncated",
                    rec.get("rid"), rec["remaining"], budget)
            h = engine.submit(
                ids,
                max_new_tokens=rec["remaining"],
                temperature=rec["temperature"],
                top_p=rec["top_p"],
                repeat_penalty=rec["repeat_penalty"],
                prime_penalty_tokens=rec.get("penalty_context",
                                             rec["out_tokens"]),
                priority=rec.get("priority"),
                # durable serving (serve/journal.py): the key
                # re-registers so a client retry attaches, and the
                # replay coordinate marks which of `ids` are folded
                # PRIOR generations — SSE event ids and the journal's
                # original-stream re-seed both count from it
                idempotency_key=rec.get("idempotency_key"),
                replay_tokens=(list(rec.get("replayed") or ())
                               + list(rec["out_tokens"])),
            )
            tracer = getattr(engine, "tracer", None)
            if tracer is not None:
                tracer.annotate(h._req.rid, resumed=True,
                                truncated=truncated)
                if rec["out_tokens"] or rec.get("replayed"):
                    # the explain timeline names the resume: this
                    # stream's earlier history was replayed from a
                    # snapshot/journal, not generated in this epoch
                    tracer.span(h._req.rid, "replayed",
                                journal_rid=rec.get("rid"),
                                generated=(len(rec["out_tokens"])
                                           + len(rec.get("replayed")
                                                 or ())))
            resumed_c.inc()
            handles.append(h)
        except Exception as e:  # noqa: BLE001 — one bad record must not
            # crash-loop server startup (queue full, shrunk max_seq_len, …)
            log.warning("resume: dropping request rid=%s: %s",
                        rec.get("rid"), e)
            dropped_c.inc()
            rec = dict(rec, error=f"resume failed: {e}")
            finished.append(rec)
    log.info("resume: %d request(s) resubmitted, %d already finished",
             len(handles), len(finished))
    return handles, finished


def restore(engine, path: str, strict: bool = True) -> Tuple[List, List[Dict]]:
    """load + resume in one call; a corrupt/unreadable snapshot (load
    -> None) restores nothing."""
    snap = load(path)
    if snap is None:
        return [], []
    return resume(engine, snap, strict=strict)
