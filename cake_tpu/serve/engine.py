"""Continuous-batching inference engine.

The reference serves one request at a time — the REST handler write-locks
the whole Master for the duration of a generation (api/text.rs:67,
SURVEY.md §3.3). This engine replaces that with slot-based continuous
batching on top of the native scheduler (cake_tpu/native/scheduler.py):

  * a fixed pool of B decode slots shares ONE batched KV cache
    [L, B, T, KV, hd] — static shapes, so the decode step is a single
    cached XLA program regardless of which requests occupy which slots;
  * new requests are admitted *between decode steps*: `prefill_slot`
    fills exactly one slot's cache lines (dynamic_slice / update along the
    batch axis) while neighboring slots keep decoding next iteration;
  * every slot carries its own position, PRNG key, repeat-penalty ring and
    sampling options, so the batched step is "ragged": per-row RoPE rows,
    per-row causal masks, per-row temperature/top_p
    (model.forward_ragged, ops/sampling.sample_tokens_ragged);
  * tokens stream to per-request callbacks from the engine thread; EOS /
    max-token retirement frees the slot for the next queued request.

A row's output depends only on its own prompt, options and PRNG key — not
on which other requests happen to share the batch (verified by
tests/test_engine.py against the sequential generator).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.analysis import engine_thread_only
from cake_tpu.models.chat import History, Message
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import steps as obs_steps
from cake_tpu.obs.events import EventBus
from cake_tpu.obs.slo import SLOAccountant, parse_slo_targets
from cake_tpu.obs.tracing import RequestTracer
from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    bucket_length, encode_text, incremental_decode,
)
from cake_tpu.models.llama.model import (
    RopeTables, decode_step_ragged, prefill_slot, prefill_slot_prefixed,
)
from cake_tpu.ops.sampling import (
    SamplingConfig, sample_tokens_ragged, update_ring_per_row,
)
from cake_tpu.sched import (
    SchedConfig, ShedController, ShedError, make_scheduler,
)
from cake_tpu.sched.classes import CLASS_RANK, validate_priority

log = logging.getLogger(__name__)

# a failed post-error rebuild bricks the engine thread; the counter makes
# that state visible on /api/v1/metrics instead of only in the logs
_RESET_FAILURES = obs_metrics.counter(
    "cake_engine_reset_failures_total",
    "Post-error engine resets that themselves failed (engine stopped)")

# paged-engine device-step wall latency (dispatch+fetch, sampling
# included), split by path — the observable the fold->pallas kernel
# switch moves; scan/burst decodes observe their per-step average so
# fold and pallas histograms compare like for like at any decode_scan
_PAGED_ATTN_STEP = obs_metrics.histogram(
    "cake_paged_attn_step_seconds",
    "Paged-engine step wall latency by path (prefill|decode|mixed)",
    labelnames=("path",))

# page-granular prefix sharing (the paged engine's prompt-cache path):
# the gauge tracks how many pool pages are currently backing more than
# their first mapping (capacity the pool did NOT have to spend), the
# counters how often and how many prompt tokens the sharing saved
_PREFIX_PAGES_SHARED = obs_metrics.gauge(
    "cake_prefix_pages_shared",
    "Shared prefix pages currently mapped into admitted slots' table "
    "rows (pool pages saved vs unshared admission)")
_PREFIX_PAGED_HITS = obs_metrics.counter(
    "cake_prefix_paged_hits_total",
    "Paged prefills served from pool-resident shared prefix pages")
_PREFIX_TOKENS_SAVED = obs_metrics.counter(
    "cake_prefix_tokens_saved_total",
    "Prompt tokens whose prefill was skipped via a cached prefix")

# SLO-aware scheduling (cake_tpu/sched): preemption/shed outcomes and
# per-class queue state — the observables behind the 429 contract and
# the bench --slo tier's preemption-on-vs-off comparison
_PREEMPTIONS = obs_metrics.counter(
    "cake_preemptions_total",
    "Decoding slots preempted for a starved higher priority class, by "
    "trigger (slots = slot-starved, pages = kv-page-starved)",
    labelnames=("reason",))
_SHED_REQUESTS = obs_metrics.counter(
    "cake_shed_requests_total",
    "Requests rejected by per-class load shedding (HTTP 429 with a "
    "computed Retry-After)",
    labelnames=("class",))
_QUEUE_DEPTH = obs_metrics.gauge(
    "cake_queue_depth",
    "Queued requests by priority class (SLO scheduler; refreshed at "
    "submit, each engine iteration, and metrics scrape)",
    labelnames=("class",))
_SCHED_TTFT = obs_metrics.histogram(
    "cake_sched_ttft_seconds",
    "Submit-to-first-token latency by priority class (includes queue "
    "wait and any preemption-induced requeues)",
    labelnames=("class",))

# crash recovery (cake_tpu/faults + _attempt_recovery): the observables
# behind the "one transient fault must not wipe a batch" contract —
# recovery outcomes, requests carried across a reset, and requests
# quarantined as poison so their batch could recover
_RECOVERIES = obs_metrics.counter(
    "cake_engine_recoveries_total",
    "Engine step-failure recovery attempts by outcome (recovered = "
    "reset + in-flight requests resubmitted; storm_breaker = too many "
    "resets in the window, snapshot + clean stop; reset_failed = the "
    "rebuild itself failed, engine stopped)",
    labelnames=("outcome",))
_RECOVERED_REQUESTS = obs_metrics.counter(
    "cake_requests_recovered_total",
    "In-flight requests carried across an engine reset via the "
    "fold-tokens-into-prompt resubmit (no client-visible failure)")
_POISON_REQUESTS = obs_metrics.counter(
    "cake_poison_requests_total",
    "Requests quarantined with a typed non-retryable error, by reason "
    "(implicated = present in implication_budget consecutive failed "
    "steps; resubmit_failed = recovery could not requeue it)",
    labelnames=("reason",))
_RECOVERY_SECONDS = obs_metrics.histogram(
    "cake_engine_recovery_seconds",
    "Wall seconds from deciding to recover to every surviving request "
    "requeued (backoff wait + cache rebuild + resubmission)")


@dataclass
class _Request:
    rid: int
    prompt_ids: List[int]
    max_new_tokens: int
    temperature: float
    top_p: float
    repeat_penalty: float
    # (delta, is_final) — or (delta, is_final, n_done) when the callback
    # declares wants_count (see stream_wants_count below)
    stream: Optional[Callable[..., None]]
    # stream callback declared `wants_count = True`: it is called with a
    # third argument, the number of finalized (token, logprob, top) entries
    # up to and including this delta — snapshotted on the engine thread so
    # streamed logprob entries pair exactly with the delta carrying their
    # text (api/server.py streaming logprobs)
    stream_wants_count: bool = False
    # previously-generated tokens whose penalty state must be reconstructed
    # (checkpoint resume): seeds the slot's repeat-penalty ring
    prime_tokens: List[int] = field(default_factory=list)
    # request asked for top-N alternatives (OpenAI top_logprobs): the
    # extra lax.top_k + host transfer is only paid while such a request
    # is in the batch
    want_top: bool = False
    # SLO scheduling (cake_tpu/sched): admission class and how many
    # times this request's slot has been reclaimed for a higher class
    priority: str = "standard"
    preemptions: int = 0
    # crash-implication tracking (_attempt_recovery): consecutive
    # failed steps this request was dispatched in; reset to 0 by any
    # step that emits for it, quarantined as poison at the budget
    crash_count: int = 0
    # durable serving (serve/journal.py): the client's idempotency key
    # (x-cake-idempotency-key — a retried submit with the same key
    # attaches instead of double-admitting), and the tokens generated
    # in PREVIOUS process generations that a cold-restart replay folded
    # into prompt_ids. The request's ABSOLUTE stream position — SSE
    # event ids, journal emit counts — is len(replayed_tokens) +
    # len(out_tokens).
    idempotency_key: Optional[str] = None
    replayed_tokens: List[int] = field(default_factory=list)
    out_tokens: List[int] = field(default_factory=list)
    out_logprobs: List[float] = field(default_factory=list)
    # per emitted token: [(alt_token_id, alt_logprob), ...] top-N list
    out_top: List[list] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[Exception] = None
    slot: int = -1
    # spilled-victim resume (cake_tpu/kv host tier): set by
    # _alloc_slot_pages when the slot's KV was restored from host RAM
    # — the admission path then skips the recompute prefill entirely
    _kv_restored: bool = False
    # disaggregated serving (cake_tpu/kv/transfer.py): on the PREFILL
    # host, the callback handed the captured page shipment at
    # retirement; on the DECODE host, True while the admission is
    # parked awaiting the peer's shipment (disagg_complete enters it
    # into the scheduler)
    ship_sink: Optional[Callable] = None
    _disagg_pending: bool = False
    submit_t: float = 0.0
    first_token_t: float = 0.0
    finish_t: float = 0.0
    _pending_text: str = ""


class RequestHandle:
    """Caller-side view of a submitted request."""

    def __init__(self, req: _Request, tokenizer, eos_ids):
        self._req = req
        self._tokenizer = tokenizer
        self._eos_ids = eos_ids

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._req.done.wait(timeout)

    def finished(self) -> bool:
        """True once the request retired (tokens final or error set) —
        non-blocking; the disagg prefill plane's writer uses this to
        spot admissions that died before capturing a shipment."""
        return self._req.done.is_set()

    @property
    def token_ids(self) -> List[int]:
        ids = self._req.out_tokens
        return [t for t in ids if t not in self._eos_ids]

    @property
    def token_logprobs(self) -> List[tuple]:
        """(token_id, logprob) pairs aligned with token_ids (EOS dropped;
        the OpenAI `logprobs` content)."""
        return [(t, lp) for t, lp in zip(self._req.out_tokens,
                                         self._req.out_logprobs)
                if t not in self._eos_ids]

    @property
    def token_top_logprobs(self) -> List[list]:
        """Per emitted token, the top-N most probable alternatives as
        [(token_id, logprob), ...] (the OpenAI `top_logprobs` content),
        aligned with token_ids (EOS dropped)."""
        return [top for t, top in zip(self._req.out_tokens,
                                      self._req.out_top)
                if t not in self._eos_ids]

    def text(self) -> str:
        if self._req.error is not None:
            raise self._req.error
        return self._tokenizer.decode(self.token_ids)

    @property
    def ttft(self) -> float:
        """Seconds from submit to first token (includes queueing)."""
        r = self._req
        return (r.first_token_t - r.submit_t) if r.first_token_t else 0.0

    @property
    def tokens_per_s(self) -> float:
        r = self._req
        n = len(r.out_tokens)
        dt = (r.finish_t or time.perf_counter()) - (r.first_token_t or 0)
        return (n - 1) / dt if n > 1 and dt > 0 else 0.0


@dataclass
class EngineStats:
    """Aggregate throughput counters (reference worker.rs:254-283 analog)."""

    steps: int = 0
    tokens_generated: int = 0
    requests_completed: int = 0
    decode_time_s: float = 0.0
    prefill_time_s: float = 0.0
    prefix_hits: int = 0     # prefills served from a registered prefix
    errors: int = 0
    last_error: str = ""
    # SLO scheduling: slots reclaimed for a higher class / requests
    # rejected by load shedding (cake_tpu/sched)
    preemptions: int = 0
    shed: int = 0
    # KV host tier (cake_tpu/kv): spill/restore EVENTS (the
    # cake_kv_spill_total counters count pages); resident spills are
    # the subset that parked an ACTIVELY-DECODING stream to admit a
    # new one (pool oversubscription, cake_kv_resident_spills_total)
    kv_spills: int = 0
    kv_restores: int = 0
    kv_resident_spills: int = 0
    # disaggregated serving (cake_tpu/kv/transfer.py): shipments
    # captured on the prefill host / shipped prefills adopted on the
    # decode host (the wire counters are cake_kv_ship_total et al.)
    kv_ships: int = 0
    kv_adopts: int = 0
    # crash recovery (cake_tpu/faults): successful reset+resubmit
    # cycles, requests carried across them, and requests quarantined
    # as poison so the rest of their batch could recover
    recoveries: int = 0
    requests_recovered: int = 0
    poisoned: int = 0
    # live reconfiguration (cake_tpu/autotune): completed hot switches
    # and guard-driven reverts (engine.reconfigure)
    config_switches: int = 0
    config_rollbacks: int = 0
    # speculative engine mode: drafts offered / kept across all slots
    spec_proposed: int = 0
    spec_accepted: int = 0

    @property
    def spec_acceptance(self) -> float:
        return (self.spec_accepted / self.spec_proposed
                if self.spec_proposed else 0.0)

    @property
    def decode_tokens_per_s(self) -> float:
        return (self.tokens_generated / self.decode_time_s
                if self.decode_time_s > 0 else 0.0)


class InferenceEngine:
    """Slot-based continuous batching over one shared batched KV cache."""

    # -- cakelint vocabulary (tools/cakelint.py, cake_tpu/analysis/) ----
    # Machine-checked threading discipline; the prose invariants these
    # encode used to live only in comments here and in two source-scan
    # tests. ENGINE_THREAD_ATTRS is single-writer engine-thread state:
    # the mapped lock (if any) is the ONE lock whose holder may touch
    # the attr from a handler thread; None means only
    # _run_on_engine_thread reaches it. HANDLER_THREAD_METHODS are the
    # entry points that run on HTTP handler / scrape / signal / health
    # threads and are statically checked against that table.
    ENGINE_THREAD_ATTRS = {
        # paged pool + page-table row state (the pager swaps wholesale
        # during a live reconfigure — admission reads its bounds under
        # the same lock the switch holds)
        "_pager": "_switch_lock",
        # slot -> request mapping and the per-slot device mirrors:
        # written only between device steps by the engine loop
        "_slot_req": None,
        "_mixed_pending": None,
        "_implicated": None,
        "_last_jit": None,
        "_page_starved": None,
        "_pending_page_preempt": None,
        # decode-resident spill state (_spill_resident_stream): the
        # admission-order stamp for LRU victim choice, the iteration's
        # decode-resident candidate set, and the parked flag that
        # forces the decode dispatch to re-validate its (stale) plan
        "_admit_seq": None,
        "_cur_decode": None,
        "_resident_parked": None,
        # handler<->engine mailboxes: strictly lock-guarded
        "_cancel_q": "_rid_lock",
        "_cmd_q": "_rid_lock",
        # disaggregated serving: shipments staged by the decode plane's
        # channel thread (disagg_complete) for the engine thread's
        # adoption in _do_prefill/_mixed_admit
        "_adopt_store": "_rid_lock",
    }
    HANDLER_THREAD_METHODS = (
        "submit", "chat", "cancel", "stop", "begin_drain",
        "drain_state", "_drain_eta_s", "register_prefix",
        "unregister_prefix", "_auto_register_system",
        "_attach_idempotent", "seed_finished_idempotent",
        "reconfigure", "request_timeline", "recovery_state",
        "autotune_state", "current_config", "_set_queue_gauges",
        "shutdown_save", "_snapshot_before_fail", "_fail_all",
        "disagg_complete",
    )
    # optional subsystems (None = disabled plane): every dotted use
    # must sit under an `is not None` guard so a disabled plane costs
    # exactly one attribute read per site (the --fault-plan injector
    # discipline, generalized)
    OPTIONAL_PLANES = ("_faults", "events", "_journal", "_shed",
                       "_control", "_host_tier", "_autotuner",
                       "telemetry", "sentinel", "_actions",
                       "_postmortem", "_disagg", "_specp")
    # the only legal nesting order; _rid_lock sits on the submit/emit
    # hot path, so nothing may block under it
    LOCK_ORDER = ("_switch_lock", "_rid_lock", "_ckpt_lock")
    NO_BLOCKING_UNDER = ("_rid_lock",)

    def __init__(
        self,
        config: LlamaConfig,
        params,
        tokenizer,
        *,
        max_slots: int = 8,
        max_seq_len: int = 4096,
        max_queue: int = 1024,
        sampling: Optional[SamplingConfig] = None,
        seed: int = 299792458,
        cache_dtype=jnp.bfloat16,
        step_fns=None,
        cache: Optional[KVCache] = None,
        decode_scan_steps: int = 1,
        auto_prefix_system: bool = False,
        max_auto_prefixes: int = 8,
        prefill_chunk: Optional[int] = None,
        top_logprobs_cap: int = 20,
        ring: Optional[bool] = None,
        draft_params=None,
        draft_config=None,
        spec_gamma: int = 4,
        spec_draft_params=None,
        spec_draft_config=None,
        kv_pages: Optional[int] = None,
        kv_page_size: int = 128,
        paged_attn: Optional[str] = None,
        kv_dtype: Optional[str] = None,
        kv_host_pages: Optional[int] = None,
        mixed_batch: Optional[str] = None,
        prompt_limit: Optional[int] = None,
        decode_budget: Optional[int] = None,
        trace_events: Optional[str] = None,
        trace_ring: int = 256,
        step_log: Optional[str] = None,
        step_ring: int = 512,
        event_log: Optional[str] = None,
        event_ring: int = 1024,
        slo_targets=None,
        priority_classes: bool = False,
        preemption: Optional[bool] = None,
        shed: bool = False,
        sched_config: Optional[SchedConfig] = None,
        fault_plan: Optional[str] = None,
        recovery: Optional[bool] = None,
        recovery_config=None,
        journal: Optional[str] = None,
        journal_fsync: str = "batch",
        autotune: Optional[str] = None,
        autotune_policy=None,
        autotune_config=None,
        sentinel: bool = False,
        sentinel_interval: float = 2.0,
        sentinel_act: bool = False,
        postmortem_dir: Optional[str] = None,
        disagg: Optional[str] = None,
        disagg_peer: Optional[str] = None,
        disagg_token: Optional[str] = None,
        disagg_timeout_s: float = 30.0,
    ):
        self.config = config
        self.params = params
        self.tokenizer = tokenizer
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        # windowed layouts (the sp engine: prompt region + decode tail)
        # bound prompts and per-request generation separately from
        # max_seq_len; None = the classic single-window rules
        self.prompt_limit = prompt_limit
        self.decode_budget = decode_budget
        self.defaults = sampling or SamplingConfig()
        # alternatives computed per sample step for OpenAI `top_logprobs`
        # (requests slice their n <= cap host-side; 20 is the API maximum;
        # one lax.top_k over [B, V] — noise next to the forward pass)
        self.n_top = top_logprobs_cap
        self.rope = RopeTables.create(config, max_seq_len)
        # step_fns: (prefill_slot_fn, decode_ragged_fn) replacements with
        # the same signatures as model.prefill_slot/decode_step_ragged —
        # e.g. parallel.pipeline.make_engine_step_fns for topology-sharded
        # serving. cache: optional pre-placed KV cache (must match the step
        # fns' sharding contract).
        # step_fns: 2-4 fns replacing the built-in jitted steps —
        # (prefill_slot_fn, decode_ragged_fn[, decode_scan_fn
        # [, prefill_chunk_fn]]), e.g. parallel.pipeline
        # .make_engine_step_fns for topology-sharded serving. With the
        # scan/chunk fns present, multi-step decode and chunked prefill
        # work over the pipeline exactly as on the built-in path.
        # ring: None = auto (builtin path decides from config); True =
        # the caller's custom step fns operate on a ring cache (pipelined
        # sliding-window serving — make_engine passes ring step fns AND a
        # W-length sharded cache together)
        self.ring = False
        if step_fns is None:
            from cake_tpu.models.llama.model import prefill_slot_chunk
            self._prefill_slot = prefill_slot
            self._decode_step = decode_step_ragged
            self._decode_scan_impl = _decode_scan
            self._prefill_chunk_step = prefill_slot_chunk
            if (config.sliding_window is not None
                    and config.sliding_window < max_seq_len):
                # ring-buffer KV cache: a sliding-window model never
                # attends past `window`, so the cache holds only W =
                # window slots (position p -> slot p % W) — KV memory
                # drops to window/max_seq of dense. All prompts prefill
                # through the ring chunk fn (windows <= W keep scatter
                # indices unique); decode writes wrap modularly.
                from cake_tpu.models.llama.model import (
                    decode_step_ragged_ring, prefill_slot_chunk_ring,
                )
                self.ring = True
                self._decode_step = decode_step_ragged_ring
                self._prefill_chunk_step = prefill_slot_chunk_ring
                self._decode_scan_impl = _decode_scan_ring
        else:
            fns = tuple(step_fns)
            self._prefill_slot, self._decode_step = fns[0], fns[1]
            self._decode_scan_impl = fns[2] if len(fns) > 2 else None
            self._prefill_chunk_step = fns[3] if len(fns) > 3 else None
            if ring:
                if self._prefill_chunk_step is None:
                    raise ValueError(
                        "ring step fns require a chunked-prefill variant "
                        "(every ring prompt prefills in windows <= W)")
                self.ring = True
        # decode_scan_steps > 1: when no request is waiting, run K decode
        # steps as ONE on-device lax.scan per host round-trip — host/tunnel
        # dispatch latency amortizes across K tokens.
        if decode_scan_steps < 1:
            raise ValueError("decode_scan_steps must be >= 1")
        if decode_scan_steps > 1 and self._decode_scan_impl is None:
            log.warning(
                "decode_scan_steps=%d ignored: these custom step fns "
                "provide no scan variant", decode_scan_steps)
            decode_scan_steps = 1
        self._decode_scan = decode_scan_steps
        # prefix caching capability: builtin dense path, or a pipelined
        # path with a chunked-prefill variant (the suffix windows at
        # pos0 = P through it). Ring caches own their layout (install
        # writes dense positions) and multi-host serving would need the
        # registration replayed (attach_control re-checks) — both refuse.
        self._prefix_capable = (
            not self.ring
            and (self._prefill_slot is prefill_slot
                 or self._prefill_chunk_step is not None))
        # prefill_chunk: admit prompts longer than C in fixed C-token
        # windows (one compiled program for every prompt length; bounded
        # activation memory). Same divisibility contract as the
        # generator's knob — a clamped final window would overwrite
        # earlier cache entries.
        if prefill_chunk is not None and self._prefill_chunk_step is None:
            # check BEFORE validation: an engine whose step fns lack a
            # chunk variant ignores the knob with a warning, not a crash
            log.warning("prefill_chunk ignored: these custom step fns "
                        "provide no chunked-prefill variant")
            prefill_chunk = None
        if self.ring:
            # every prefill must be a ring window <= W
            W = config.sliding_window
            prefill_chunk = min(prefill_chunk or min(512, W), W)
        if prefill_chunk is not None and (
                prefill_chunk < 1 or max_seq_len % prefill_chunk != 0):
            raise ValueError(
                f"prefill_chunk {prefill_chunk} must be >= 1 and divide "
                f"max_seq_len {max_seq_len}"
                + (" (ring/sliding-window serving requires a chunk that "
                   "divides max_seq_len; pass --prefill-chunk)"
                   if self.ring else ""))
        # speculative decoding INSIDE the engine (round-5: the former
        # single-request island now composes with API batching and
        # checkpointing): a draft model proposes spec_gamma tokens per
        # slot round, the target verifies them in one pass
        # (speculative.spec_round_batched), and the engine batches
        # rounds across slots — each round emits 1..gamma+1 tokens.
        self._spec = draft_params is not None
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.spec_gamma = spec_gamma
        if self._spec:
            if step_fns is not None or self.ring:
                raise ValueError(
                    "the speculative engine requires the built-in dense "
                    "single-device path (no topology/ring step fns)")
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    "draft and target must share a vocabulary")
            if prefill_chunk is not None:
                log.warning("prefill_chunk ignored in speculative mode "
                            "(whole-prompt prefill keeps the draft cache "
                            "aligned)")
                prefill_chunk = None
            if self._decode_scan > 1:
                log.warning("decode_scan ignored in speculative mode "
                            "(each spec round already amortizes up to "
                            "gamma+1 tokens per dispatch)")
                self._decode_scan = 1
            # a prefix-cached target prefill would leave the draft cache
            # cold at those positions — acceptance would silently
            # collapse; keep the caches aligned instead
            self._prefix_capable = False
            self.d_rope = RopeTables.create(draft_config, max_seq_len)
        # PAGED speculative decoding (cake_tpu/spec): spec as a row
        # KIND of the paged engine, not a separate engine — a draft
        # model's KV lives in a second paged pool addressed by the SAME
        # page allocator, streams opt in lazily per-row (incompatible
        # sampling simply decodes plain), and acceptance truncates the
        # speculative suffix pages back to the pool every round.
        self._spec_paged = spec_draft_params is not None
        self._specp = None
        if self._spec_paged:
            from cake_tpu.spec import SpecPlane
            if self._spec:
                raise ValueError(
                    "--spec-draft (paged spec rows) and --draft-model "
                    "(the dense spec engine) are mutually exclusive")
            if kv_pages is None:
                raise ValueError(
                    "--spec-draft requires --kv-pages: paged "
                    "speculative decoding shares the page allocator "
                    "(use --draft-model for the dense spec engine)")
            if kv_dtype in ("int8", "int4"):
                raise ValueError(
                    f"--spec-draft requires f32/bf16 KV pages, got "
                    f"--kv-dtype {kv_dtype}: the draft pool has no "
                    "quantized flavor yet (ROADMAP item 3)")
            if spec_draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    "spec draft and target must share a vocabulary")
            if spec_gamma < 1:
                raise ValueError(
                    f"spec_gamma must be >= 1, got {spec_gamma}")
            from cake_tpu.autotune.spec import SpecGammaTuner
            self._specp = SpecPlane(
                spec_draft_params, spec_draft_config, spec_gamma,
                rope=RopeTables.create(spec_draft_config, max_seq_len),
                tuner=SpecGammaTuner(gamma=spec_gamma))
        # paged KV (round-5, the 32-slot HBM-thrash fix): KV lives in a
        # shared pool of kv_pages fixed-size pages; slots map position
        # ranges through a table and the page ALLOCATOR gates admission,
        # so resident KV is bounded by the pool, not slots x max_seq_len
        # (models/llama/paged.py).
        self.paged = kv_pages is not None
        self.paged_attn: Optional[str] = None
        # --kv-dtype: storage dtype for the PAGED pool. "int8"/"int4"
        # select the quantized page pools (cake_tpu/kv: int8 pages or
        # nibble-packed int4 pages + per-page per-kv-head f32 scales —
        # ~4x / ~8x the resident streams per pool byte vs f32); other
        # names resolve to a plain pool dtype. Quantized KV without
        # --kv-pages (the spec engine included: spec is gated off
        # paged) is a loud config error, not a silent no-op.
        self.kv_quant = kv_dtype in ("int8", "int4")
        # config identity the live-reconfiguration seam (reconfigure /
        # cake_tpu/autotune) needs verbatim: the configured storage
        # name, the base cache dtype, the host-tier capacity and the
        # custom-step marker — a rebuilt pool must resolve exactly as
        # the startup one did
        self._kv_dtype_name = kv_dtype
        self._base_cache_dtype = cache_dtype
        self._kv_host_pages = kv_host_pages
        self._custom_steps = step_fns is not None
        # cross-subsystem event bus (obs/events.py), created BEFORE the
        # paged setup so the host tier can attach to it: preemption,
        # KV spill/restore, prefix hits, recovery, switches, shedding,
        # fault injections and recompiles all publish request-linked
        # events here (GET /api/v1/events; --event-log JSONL sink).
        # --event-ring 0 disables the plane: self.events is then None
        # and every publish site costs one attribute test (the
        # --fault-plan injector discipline, pinned by a source scan)
        self.events = (EventBus(capacity=event_ring, log_path=event_log)
                       if event_ring > 0 else None)
        # SLO attainment + goodput accounting (obs/slo.py): per-class
        # targets from --slo-targets (defaults otherwise), fed from
        # the tracer's finish seam so TTFT/e2e verdicts use the
        # ORIGINAL admission clock across resubmits
        self.slo = SLOAccountant(
            slo_targets if isinstance(slo_targets, dict)
            else parse_slo_targets(slo_targets))
        if self.kv_quant and not self.paged:
            raise ValueError(
                f"--kv-dtype {kv_dtype} requires --kv-pages: quantized "
                "KV pages live in the paged pool"
                + (" (speculative serving is gated off the paged "
                   "engine, so it cannot quantize KV)" if self._spec
                   else ""))
        self._host_tier = None
        # pid -> monotonic last-hit time (the cold-prefix LRU order)
        self._prefix_last_hit: dict = {}
        if self.paged:
            if step_fns is not None or self.ring or self._spec:
                raise ValueError(
                    "--kv-pages requires the built-in dense single-"
                    "device path (no topology/ring/speculative mode)")
            if cache is not None:
                raise ValueError(
                    "--kv-pages builds its own page pool; a pre-placed "
                    "cache= cannot apply")
            self._setup_paged_exec(kv_pages, kv_page_size, paged_attn,
                                   kv_host_pages)
        elif kv_host_pages is not None:
            log.warning("--kv-host-pages ignored: the host KV tier "
                        "spills paged pool pages (set --kv-pages)")
        self.prefill_chunk = prefill_chunk
        # --mixed-batch {auto,on,off}: token-level continuous batching
        # for the paged engine — admissions' prefill chunks join the
        # very next mixed step alongside decode rows instead of waiting
        # for a decode pause. auto = on for paged serving, off
        # elsewhere (the dense/ring/spec engines keep their phase
        # loops); "on" without --kv-pages is a config error, not a
        # silent no-op.
        mb = mixed_batch or "auto"
        if mb not in ("auto", "on", "off"):
            raise ValueError(
                f"--mixed-batch must be auto, on or off, got {mb!r}")
        if mb == "on" and not self.paged:
            raise ValueError(
                "--mixed-batch on requires --kv-pages: the mixed "
                "ragged step dispatches over the paged pool")
        self._mixed = self.paged and mb != "off"
        if self._spec_paged and not self._mixed:
            raise ValueError(
                "--spec-draft requires the mixed ragged step "
                "(--mixed-batch auto/on): spec rows join the one-launch "
                "mixed iteration, they have no phase-loop flavor")
        # slot -> in-flight prefill progress (req, remaining window
        # offsets); teardown paths clear entries via
        # _release_slot_pages so cancel/preempt/error cannot leave a
        # ghost chunk row in the next mixed step
        self._mixed_pending: dict = {}
        # fixed mixed-chunk width: prompts walk the mixed step C tokens
        # per iteration — ONE compiled program for every prompt length
        # (a per-bucket width would recompile the hottest program)
        self._mixed_chunk = (prefill_chunk if prefill_chunk is not None
                             else min(256, max_seq_len))
        cache_len = (config.sliding_window if self.ring else max_seq_len)
        if not self.paged:
            self.cache = cache if cache is not None else KVCache.create(
                config, max_slots, cache_len, dtype=cache_dtype)
        if self._spec:
            self.d_cache = KVCache.create(draft_config, max_slots,
                                          cache_len, dtype=cache_dtype)
        # remember placement so the post-error rebuild (see _run) restores
        # an identically-sharded cache even after donation freed the buffers
        self._capture_cache_identity()
        # SLO-aware scheduling (cake_tpu/sched): priority-class queues
        # with anti-starvation aging replace FIFO admission; preemption
        # recompute-folds a lower-class slot back into the queue when a
        # higher class is slot- or page-starved; shedding turns
        # overload into honest 429s. The FIFO native scheduler stays
        # the priority-free fallback.
        self._sched_cfg = sched_config or SchedConfig()
        self._slo = bool(priority_classes)
        can_preempt = not self._spec and self.decode_budget is None
        if preemption is None:
            self._preemption = self._slo and can_preempt
        else:
            self._preemption = bool(preemption)
        if self._preemption and not self._slo:
            log.warning("--preemption requires --priority-classes; "
                        "preemption disabled")
            self._preemption = False
        if self._preemption and not can_preempt:
            log.warning(
                "preemption disabled: %s",
                "speculative serving keeps the draft cache aligned "
                "with the target per round (no recompute-resume path)"
                if self._spec else
                "windowed (ctx+tail) layouts cannot fold generated "
                "tokens back into the prompt window")
            self._preemption = False
        # crash recovery (the fail-everything replacement): on a step
        # failure, snapshot-classify-reset-RESUBMIT the in-flight
        # requests through the checkpoint fold-tokens-into-prompt path
        # instead of failing them all. Auto-on wherever the fold works
        # (the same flavors preemption can resume); speculative and
        # windowed (ctx+tail) engines keep the legacy fail-all path.
        from cake_tpu.serve.errors import RecoveryConfig
        self._recovery_cfg = recovery_config or RecoveryConfig()
        if recovery is None:
            self._recover = can_preempt
        else:
            self._recover = bool(recovery)
            if self._recover and not can_preempt:
                log.warning(
                    "crash recovery disabled: %s",
                    "speculative serving has no recompute-resume fold"
                    if self._spec else
                    "windowed (ctx+tail) layouts cannot fold generated "
                    "tokens back into the prompt window")
                self._recover = False
        # reset-storm breaker state: monotonic times of recent resets
        # (recovered OR legacy), consecutive-reset counter for backoff,
        # and a bounded recovery-latency log for bench --chaos
        self._reset_times: List[float] = []
        self._consec_resets = 0
        self.recovery_seconds: List[float] = []
        self._breaker_tripped = False
        # deterministic fault injection (cake_tpu/faults): None without
        # a --fault-plan — every site guard is then one attribute test
        from cake_tpu.faults import build_injector
        self._faults = build_injector(fault_plan)
        if self._faults is not None:
            # firings ride the event bus too (None stays None: the
            # injector's publish site guards `is not None` like ours)
            self._faults.events = self.events
            log.warning("fault plan armed: %s",
                        self._faults.plan.describe())
        # rids dispatched in the CURRENT device step — the blast radius
        # the recovery path implicates on failure (overwritten by every
        # dispatch; a failure before any dispatch implicates nobody)
        self._implicated: Sequence = ()
        # durable serving (serve/journal.py): --journal arms a
        # write-ahead request journal — admissions, emitted-token
        # batches and retire tombstones, replayed at cold restart so a
        # kill -9 loses no stream. None without the flag: every call
        # site below is one attribute test (the --fault-plan injector
        # discipline, pinned by a source-scan test).
        self._journal = None
        if journal:
            from cake_tpu.serve.journal import RequestJournal
            self._journal = RequestJournal(journal, fsync=journal_fsync)
            self._journal.faults = self._faults
            self._journal.owner = self
            log.info("request journal armed: %s (fsync=%s)", journal,
                     journal_fsync)
        # idempotent-submit registry: key -> live rid, and a bounded
        # ring of FINISHED keyed requests so a retry that lands after
        # retirement still attaches to the completed stream instead of
        # re-running it. Both guarded by _rid_lock.
        self._idem_live: dict = {}
        self._idem_done: "OrderedDict" = OrderedDict()
        self._idem_done_cap = 128
        # drain mode (POST /api/v1/drain, SIGTERM): admissions refuse
        # with a typed 429 while in-flight work finishes or snapshots
        self._draining = False
        self._shed = ShedController(self._sched_cfg) if shed else None
        # rank of a page-starved higher-class admission awaiting a
        # victim; consumed at the TOP of the next engine iteration (a
        # mid-wave preemption would leave already-planned decode rows
        # writing through a released page-table row)
        self._pending_page_preempt: Optional[int] = None
        # decode-resident spill (kv oversubscription): admission-order
        # stamp for LRU victim choice, this iteration's decode-resident
        # slots (plan()'s decode rows — NOT same-wave admissions, whose
        # prefill may be mid-flight), and the parked-this-iteration
        # flag that makes the decode dispatch re-validate its plan
        self._admit_seq = 0
        self._cur_decode: dict = {}
        self._resident_parked = False
        # retained for live reconfiguration: a hot switch that changes
        # max_slots rebuilds/resizes the scheduler at the same queue
        # capacity (reconfigure)
        self._max_queue = max_queue
        self.scheduler = make_scheduler(
            max_slots, max_queue, priority_classes=self._slo,
            config=self._sched_cfg)
        self.stats = EngineStats()
        # request-lifecycle traces (obs/tracing.py): spans recorded at
        # the submit/prefill/emit/retire seams below, so every serving
        # mode (dense, paged, spec, pipelined, sp / stage x sp / dp x
        # sp step fns) is traced identically. trace_events: optional
        # JSONL event log path (--trace-events).
        self.tracer = RequestTracer(capacity=trace_ring,
                                    events_path=trace_events,
                                    slo=self.slo)
        from cake_tpu.utils.profiling import StepStats
        self._step_stats = StepStats(name="engine", window=100)
        # step-level flight recorder + jit compile/cost accounting
        # (obs/steps.py): one record per engine step at the dispatch
        # seams below, served at GET /api/v1/steps and optionally
        # appended to --step-log. The accountant key prefix namespaces
        # this engine's config so two engines with different configs
        # (or cache dtypes) can never alias each other's compiled
        # signatures in the process-global seen-set.
        flavor = ("spec" if self._spec else
                  f"paged-{self.paged_attn}" if self.paged else
                  "ring" if self.ring else
                  "custom" if step_fns is not None else "dense")
        self.flight = obs_steps.StepTelemetry(
            impl=flavor, capacity=step_ring, log_path=step_log,
            key_prefix=(config, max_slots, max_seq_len,
                        str(self._cache_dtype), flavor),
            events=self.events)
        # latest dispatch's _JitStep (engine-thread-only mailbox between
        # the device-call seam and the step record that follows it)
        self._last_jit = None
        # distributed-trace annotation: events published with a rid
        # pick up the request's x-cake-trace id from the tracer, so
        # the front-door router's federated timeline can select this
        # replica's events by trace (one dict lookup per INCIDENT —
        # events are never per-token)
        if self.events is not None:
            self.events.trace_of = self.tracer.trace_for
        # online regression sentinel (--sentinel, obs/sentinel.py):
        # rolling-window detectors over the flight recorder / event
        # bus / SLO accountant, ticked from a daemon thread between
        # start() and stop() — zero hot-path instrumentation. None
        # without the flag (one attribute test per site, the
        # --fault-plan discipline).
        self.sentinel = None
        if sentinel:
            from cake_tpu.obs.sentinel import attach_engine_sentinel
            self.sentinel = attach_engine_sentinel(
                self, interval_s=sentinel_interval)

        B = max_slots
        self._pos = np.zeros(B, np.int64)            # next write position
        self._last_tok = np.zeros(B, np.int64)
        self._steps = np.zeros(B, np.int64)          # generated count per slot
        self._temp = np.full(B, self.defaults.temperature or 0.0, np.float32)
        self._top_p = np.ones(B, np.float32)
        self._penalty = np.full(B, self.defaults.repeat_penalty, np.float32)
        self._ring = jnp.full((B, self.defaults.repeat_last_n), -1, jnp.int32)
        self._key_seed = seed                        # for _reset_after_error
        self._reset_count = 0
        root = jax.random.PRNGKey(seed)
        self._keys = jax.random.split(root, B)       # [B] keys
        self._slot_req: List[Optional[_Request]] = [None] * B

        # registered prompt prefixes: id -> (token ids, k, v) with k/v
        # [L, 1, P, KV, hd] in cache dtype (register_prefix)
        self._prefixes: dict = {}
        self._next_prefix_id = 1
        # auto_prefix_system: chat() registers each distinct system
        # prompt's rendered head once (FIFO-capped so a public API cannot
        # grow the registry without bound). Keyed by the rendered head
        # STRING so the membership test costs no tokenization; the value
        # is None while a registration is in flight (reservation — chat()
        # runs on concurrent HTTP handler threads).
        self._auto_prefix = auto_prefix_system
        self._max_auto = max_auto_prefixes
        # head str -> prefix id | None (in-flight) | -1 (unqualifying
        # head, negative-cached) — only non-negative ids key _prefixes
        self._auto_pids: dict = {}

        # multi-host serving: the coordinator publishes each device-step
        # op through _control (serve/control.py) so follower processes
        # replay the identical SPMD dispatch; _multihost additionally
        # localizes logits so sampling is process-local + deterministic
        self._control = None
        self._multihost = False
        # follower side: seq of the last successfully APPLIED control
        # op — the exporter (obs/federation.py) ships it in telemetry
        # frames so the coordinator's fleet view can compute lag
        self.applied_op_seq = 0
        # coordinator side: an attached obs/federation
        # TelemetryCollector — request_timeline merges its remote
        # events so one explain call spans hosts
        self.telemetry = None

        self._next_rid = 1
        self._rid_lock = threading.Lock()
        # engine-thread command queue (multi-host prefix ops: their
        # device work is a cross-process collective, so it must dispatch
        # in the engine thread's program order — see _run_on_engine_thread)
        self._cmd_q: list = []
        # serializes pre-fail snapshot writes (health-monitor thread)
        # against the shutdown keep-or-save decision (signal/serve
        # thread) — without it a SIGTERM landing mid-failure could read
        # _prefail_written before the pre-fail write and clobber it
        self._ckpt_lock = threading.Lock()
        self._requests = {}
        # rids whose callers gave up (client disconnect): drained by the
        # ENGINE thread at the top of its loop, so request/slot teardown
        # has a single writer
        self._cancel_q: List[int] = []
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        # live reconfiguration (cake_tpu/autotune): --autotune
        # {off,manual,auto}. `manual` arms POST /api/v1/autotune;
        # `auto` additionally runs the policy controller from the
        # engine thread (_autotune_tick). The hot-switch seam
        # (reconfigure) exists regardless of the mode — checkpoint
        # restore and tests drive it directly.
        self.config_epoch = 0
        self._switch_lock = threading.Lock()
        self._switch_inflight = False
        self._switch_log: deque = deque(maxlen=64)
        mode = autotune or "off"
        if mode not in ("off", "manual", "auto"):
            raise ValueError(
                f"--autotune must be off, manual or auto, got {mode!r}")
        if mode != "off" and not self._reconfig_supported():
            log.warning("--autotune disabled: %s",
                        self._reconfig_refusal())
            mode = "off"
        self.autotune_mode = mode
        if mode != "off":
            # publish the STARTUP config through the info gauge: the
            # "live effective config" contract must hold before (and
            # without) any switch, not only after the first one
            from cake_tpu.autotune import set_config_info
            set_config_info(self.current_config())
        self._autotuner = None
        self._autotune_last = 0.0
        # (t, submitted, completed, tokens, shed) deltas for the
        # signal gather (_gather_autotune_signals)
        self._autotune_prev: Optional[tuple] = None
        if mode == "auto":
            from cake_tpu.autotune import (
                AutotuneController, ControllerConfig, PolicyTable,
            )
            if autotune_policy is None:
                raise ValueError(
                    "--autotune auto requires --autotune-policy (fit "
                    "one with tools/autotune_fit.py)")
            if isinstance(autotune_policy, str):
                policy = PolicyTable.load(autotune_policy)
            elif isinstance(autotune_policy, dict):
                policy = PolicyTable.from_dict(autotune_policy).validate()
            else:
                policy = autotune_policy
            policy.validate(max_seq_len=self.max_seq_len)
            self._autotuner = AutotuneController(
                policy, self.current_config(),
                config=autotune_config or ControllerConfig())
            log.info("autotune: auto mode, %d policy regime(s), "
                     "interval %.1fs",
                     len(policy.regimes),
                     self._autotuner.config.interval_s)

        # closed-loop action plane (--sentinel-act, obs/actions.py):
        # sentinel anomalies become first-class autotune signals with a
        # typed, rate-bounded, metric-counted audit trail. None without
        # the flag — report-only stays byte-identical to PR 15.
        self._actions = None
        if sentinel_act:
            if self.sentinel is None:
                raise ValueError(
                    "--sentinel-act requires --sentinel (nothing to "
                    "act on without the anomaly sentinel)")
            from cake_tpu.obs.actions import (
                ActionPlane, EngineAnomalyActuator,
            )
            self._actions = ActionPlane(events=self.events)
            EngineAnomalyActuator(self, self._actions).attach(
                self.sentinel)
        # black-box postmortem sink (--postmortem-dir): breaker stops,
        # poison quarantines, failed recoveries and SIGTERM dump one
        # forensic bundle each (tools/postmortem.py renders them)
        self._postmortem = None
        if postmortem_dir:
            from cake_tpu.obs.actions import PostmortemSink
            self._postmortem = PostmortemSink(postmortem_dir)

        # disaggregated prefill/decode (--disagg, kv/transfer.py): one
        # engine runs prefill-only and ships pool pages; the other is
        # the front door, adopting shipped prefills into its own pool.
        # _adopt_store stages reassembled shipments (channel thread ->
        # engine thread) keyed by rid; it exists even without the plane
        # so the adoption peeks stay branch-free.
        self._adopt_store = {}
        self._disagg = None
        if disagg is not None:
            if not self.paged:
                raise ValueError(
                    "--disagg requires the paged KV pool (--kv-pages): "
                    "pages are the transfer unit")
            if not disagg_peer:
                raise ValueError(
                    "--disagg requires --disagg-peer host:port (the "
                    "prefill engine binds it; the decode engine "
                    "connects to it)")
            from cake_tpu.kv.transfer import build_disagg_plane
            token = disagg_token or os.environ.get(
                "CAKE_DISAGG_TOKEN", "")
            self._disagg = build_disagg_plane(
                self, disagg, disagg_peer, token, events=self.events,
                timeout_s=disagg_timeout_s)
            log.info("disagg: %s role, peer %s", disagg, disagg_peer)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "InferenceEngine":
        if self._thread is None:
            from cake_tpu.utils.profiling import log_memory
            log_memory("engine start")
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="cake-engine")
            self._thread.start()
            if self.sentinel is not None:
                self.sentinel.start()
            if self._disagg is not None:
                self._disagg.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        if self.sentinel is not None:
            self.sentinel.close()
        if self._disagg is not None:
            # first: a decode plane degrades its in-flight shipments to
            # local prefill while the engine thread can still run them
            self._disagg.stop()
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        # catch cancellations enqueued after the engine thread's final
        # drain but before join returned (the cancel() dead-thread check
        # handles calls arriving later than this)
        # cakelint: skip[affinity] engine thread joined above: inline teardown is single-threaded; the runtime assert checks liveness
        self._drain_cancellations()
        self.tracer.close()
        self.flight.close()
        if self.events is not None:
            self.events.close()
        if self._journal is not None:
            # flush buffered emit batches + fsync: a clean stop's
            # journal is durable (the snapshot handshake may then
            # truncate it — shutdown_save)
            self._journal.close()
        if self._control is not None:
            # published only after the engine thread has exited, so no
            # step op can be ordered after the stop on the wire
            try:
                self._control.publish({"op": "stop"})
            except Exception:  # noqa: BLE001
                log.warning("control: stop publish failed (followers "
                            "will exit on channel close)")

    # -- multi-host -----------------------------------------------------------

    def attach_control(self, control) -> None:
        """Coordinator side of multi-host serving: publish every device
        step through `control` (a serve.control.ControlServer) before
        dispatching it, so every follower process enters the same SPMD
        program. Reference behavior analog: the master streaming work to
        workers (worker.rs:289-303). Must be called before start()."""
        from cake_tpu.models.llama.model import prefill_slot as _builtin
        if self._prefill_slot is _builtin or self.paged:
            raise ValueError(
                "multi-host control requires pipelined step fns (a mesh "
                "spanning processes); the single-device engine (incl. "
                "--kv-pages) has no cross-process computation to "
                "coordinate")
        if self._prefixes:
            raise ValueError(
                "multi-host control cannot be attached after prefix "
                "registrations (registrations are not replayed)")
        self._control = control
        # a --fault-plan with control.publish rules fires inside the
        # channel itself, so the failure shape (publish raises) is the
        # one a dead follower produces
        if self._faults is not None:
            control.faults = self._faults
        self._multihost = True

    def run_follower_loop(self, client,
                          reset_wait_s: float = 120.0,
                          op_timeout_s: Optional[float] = None,
                          liveness=None) -> None:
        """Non-coordinator side: replay the coordinator's op stream.
        Blocks until the coordinator publishes a stop or closes the
        channel. The engine thread is never started here — this process
        only mirrors device steps so the SPMD collectives line up.

        After a failed op this process is out of sync (its donated cache
        may be gone). The symmetric case — the collective failed on every
        process — is recovered by the coordinator's reset op. If no reset
        arrives within reset_wait_s, the failure was follower-local
        (asymmetric); the only safe move is to disconnect, which makes
        the coordinator's next publish raise and fail its requests
        instead of hanging its next collective forever.

        op_timeout_s/liveness: the follower liveness deadline. A
        coordinator that dies BETWEEN ops (kill -9, kernel panic —
        no FIN ever arrives) used to hang this process in recv()
        forever. With op_timeout_s set, each quiet interval re-checks
        `liveness()` (cli wires it to the heartbeat channel: the
        monitor lives in the coordinator process, so a sendall that
        still succeeds proves the peer is up); a quiet interval with
        liveness gone exits with a clear error instead of hanging. An
        idle-but-alive coordinator just keeps the loop waiting."""
        import socket as _socket

        self._multihost = True
        log.info("engine follower: replaying coordinator ops")
        failed = False
        while True:
            try:
                op = client.recv(
                    timeout=reset_wait_s if failed else op_timeout_s)
            except (_socket.timeout, TimeoutError):
                if not failed:
                    if liveness is not None and liveness():
                        continue    # quiet but provably alive: keep on
                    log.error(
                        "engine follower: no op for %.0fs and the "
                        "coordinator shows no liveness; exiting "
                        "instead of hanging the process", op_timeout_s)
                    return
                log.error("engine follower: op failed and no reset came "
                          "within %.0fs; disconnecting", reset_wait_s)
                return
            if op is None or op.get("op") == "stop":
                if op is not None and isinstance(op.get("seq"), int):
                    # count the stop as applied: a drained follower
                    # must report zero lag, not one phantom op
                    self.applied_op_seq = op["seq"]
                log.info("engine follower: coordinator %s",
                         "stopped" if op else "closed the channel")
                return
            if failed and op.get("op") != "reset":
                # a normal op after our failure means the coordinator's
                # twin dispatch SUCCEEDED — our mirrors may have drifted,
                # and executing more ops would silently diverge; bail
                log.error("engine follower: op %r after a local failure "
                          "(no reset) — out of sync; disconnecting",
                          op.get("op"))
                return
            try:
                kind = op["op"]
                if kind == "prefill":
                    self._prefill_device(
                        op["ids"], op["slot"], op["temp"], op["top_p"],
                        op["penalty"], op.get("prime", ()),
                        n_top=op.get("n_top", 0))
                elif kind == "decode":
                    self._decode_device(op["rows"],
                                        n_top=op.get("n_top", 0))
                elif kind == "decode_scan":
                    budget = np.asarray(
                        op.get("budget", [op["n"]] * self.max_slots),
                        np.int32)
                    toks, _lps, _ti, _tl = self._decode_scan_device(
                        op["rows"], op["n"], op["n_top"], budget=budget)
                    self._finalize_scan_mirrors(op["rows"], op["n"], toks,
                                                budget)
                elif kind == "register_prefix":
                    ids = list(op["ids"])
                    P = len(ids)
                    k, v = self._prefix_kv_device(
                        ids, P, bucket_length(P, self.max_seq_len))
                    with self._rid_lock:
                        self._prefixes[op["pid"]] = (ids, k, v)
                elif kind == "unregister_prefix":
                    with self._rid_lock:
                        self._prefixes.pop(op["pid"], None)
                elif kind == "prefill_prefixed":
                    self._prefixed_prefill_device(
                        op["pid"], op["ids"], op["slot"], op["temp"],
                        op["top_p"], op["penalty"], op.get("prime", ()),
                        n_top=op.get("n_top", 0))
                elif kind == "reset":
                    self._reset_after_error()
                else:
                    log.error("engine follower: unknown op %r", kind)
                failed = False
                if isinstance(op.get("seq"), int):
                    # applied (not merely received): telemetry frames
                    # report this, and lag vs the published seq is the
                    # fleet view's behind-ness signal
                    self.applied_op_seq = op["seq"]
            except Exception:  # noqa: BLE001
                log.exception("follower op failed (awaiting reset)")
                failed = True

    def _publish(self, op: dict) -> None:
        if self._control is not None:
            self._control.publish(op)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- submission -----------------------------------------------------------

    def submit(
        self,
        prompt_ids: Sequence[int],
        *,
        max_new_tokens: int = 100,
        temperature: Optional[float] = None,
        top_p: Optional[float] = None,
        repeat_penalty: Optional[float] = None,
        stream: Optional[Callable[..., None]] = None,
        prime_penalty_tokens: Optional[Sequence[int]] = None,
        want_top_logprobs: bool = False,
        priority: Optional[str] = None,
        idempotency_key: Optional[str] = None,
        replay_tokens: Optional[Sequence[int]] = None,
        trace_id: Optional[str] = None,
        ship_sink: Optional[Callable] = None,
    ) -> RequestHandle:
        """Queue one generation. stream(text_delta, is_final) is called from
        the engine thread as tokens finalize; a callback with attribute
        `wants_count = True` instead gets (text_delta, is_final, n_done)
        where n_done counts the finalized logprob entries up to and
        including this delta. The handle's wait()/text() gives the
        blocking interface."""
        if self._stop.is_set():
            # post-stop submits (e.g. an HTTP handler racing shutdown)
            # must not mutate state under a checkpoint snapshot; typed
            # + retryable so the API can 503 (a stopped engine is a
            # restart away from serving this same request)
            from cake_tpu.serve.errors import EngineResetError
            raise EngineResetError("engine stopped")
        if idempotency_key is not None:
            # BEFORE validation: the key names an EXISTING stream, so a
            # retry attaches regardless of what its (possibly
            # re-rendered, possibly oversized) payload looks like — the
            # original admission already validated the real work. The
            # re-check under the switch lock below closes the race of
            # two concurrent first-submits with one key.
            prev = self._attach_idempotent(idempotency_key, stream)
            if prev is not None:
                return prev
        # validate the class EVERY time (unknown values must 400 at the
        # API); the class only orders admission when the SLO scheduler
        # is on, but it always labels the TTFT histogram
        cls = validate_priority(priority)
        ids = list(prompt_ids)
        if not ids:
            raise ValueError("empty prompt")
        if len(ids) >= self.max_seq_len:
            raise ValueError(
                f"prompt length {len(ids)} exceeds max_seq_len "
                f"{self.max_seq_len}")
        if self.prompt_limit is not None and len(ids) > self.prompt_limit:
            raise ValueError(
                f"prompt length {len(ids)} exceeds this serving mode's "
                f"prompt window {self.prompt_limit}")
        max_new = min(max_new_tokens, self.max_seq_len - len(ids))
        if self.decode_budget is not None:
            # windowed layouts cap generation by the tail capacity, not
            # by max_seq - prompt
            max_new = min(max_new, self.decode_budget)
        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        d = self.defaults
        eff_temp = temperature if temperature is not None else d.temperature
        eff_top_p = top_p if top_p is not None else d.top_p
        if self._spec:
            # the accept/resample identity assumes the unfiltered
            # temperature softmax, and the verify pass scores the burst
            # in parallel (no within-burst penalty ring) — reject
            # incompatible sampling with a clean client error
            eff_pen = (d.repeat_penalty if repeat_penalty is None
                       else repeat_penalty)
            if (eff_top_p or 1.0) < 1.0 or eff_pen != 1.0:
                raise ValueError(
                    "speculative serving supports temperature-only "
                    "sampling (top_p=1, repeat_penalty=1)")
            if want_top_logprobs:
                raise ValueError(
                    "logprobs are unavailable in speculative serving "
                    "(accepted drafts are not sampled step-by-step)")
        replayed = list(replay_tokens or ())
        if replayed and ids[-len(replayed):] != replayed:
            # the replay coordinate must be a literal suffix of the
            # folded prompt (checkpoint/journal resume constructs it
            # that way); anything else would corrupt SSE event ids
            raise ValueError(
                "replay_tokens must be the folded suffix of prompt_ids")
        req = _Request(
            rid=rid, prompt_ids=ids, max_new_tokens=max_new,
            temperature=eff_temp if eff_temp is not None else 0.0,
            top_p=eff_top_p if eff_top_p is not None else 1.0,
            repeat_penalty=(d.repeat_penalty if repeat_penalty is None
                            else repeat_penalty),
            stream=stream,
            stream_wants_count=bool(getattr(stream, "wants_count", False)),
            submit_t=time.perf_counter(),
            prime_tokens=list(prime_penalty_tokens or ()),
            want_top=want_top_logprobs,
            priority=cls,
            idempotency_key=idempotency_key,
            replayed_tokens=replayed,
            ship_sink=ship_sink,
        )
        # admission critical section: a LIVE config switch
        # (_reconfigure_sync) replaces the pool/pager/scheduler on the
        # engine thread while THIS runs on a handler thread — the lock
        # makes each admission land fully before or fully after a
        # switch (never half-registered across the scheduler swap, and
        # the pool bound below always reads one consistent pool)
        with self._switch_lock:
            if idempotency_key is not None:
                # the race-closing RE-check: two concurrent first
                # submits with one key serialize here — the loser
                # attaches to the winner's admission instead of
                # double-admitting
                prev = self._attach_idempotent(idempotency_key, stream)
                if prev is not None:
                    return prev
            if self._draining and replay_tokens is None:
                # admissions are closed while the drain finishes or
                # snapshots in-flight work; replay resubmits (the
                # recovery path) must still land — they ARE the
                # in-flight work. Typed so the API maps it to 429 +
                # the computed seconds until the drain completes.
                from cake_tpu.serve.errors import DrainingError
                raise DrainingError(self._drain_eta_s())
            if self.paged and (self._pager.pages_for(len(ids) + max_new)
                               > self.cache.n_pages):
                # can NEVER be admitted (need exceeds the whole pool) —
                # fail fast instead of requeueing forever. A shared
                # prefix does not change this bound: the prefix is
                # page-aligned, so prefix pages + suffix pages == the
                # contiguous page count exactly (sharing saves FREE
                # pages at admission, not table-row size)
                raise ValueError(
                    f"request needs "
                    f"{self._pager.pages_for(len(ids) + max_new)} kv "
                    f"pages; the pool has {self.cache.n_pages} total "
                    "(raise --kv-pages or lower max_tokens)")
            if self._shed is not None:
                # AFTER every validation above: an invalid request must
                # get its deterministic 400, never a 429 inviting a
                # retry of something that can never succeed (and must
                # not pollute the shed counters)
                depth = (self.scheduler.depth_ahead(cls)
                         if hasattr(self.scheduler, "depth_ahead")
                         else self.scheduler.queue_depth)
                dec = self._shed.decide(cls, depth)
                if not dec.admit:
                    self.stats.shed += 1
                    _SHED_REQUESTS.labels(cls).inc()
                    if self.events is not None:
                        self.events.publish(
                            "shed", rid=rid, priority=cls,
                            retry_after_s=round(dec.retry_after_s, 3),
                            est_wait_s=(round(dec.est_wait_s, 3)
                                        if dec.est_wait_s is not None
                                        else None))
                    raise ShedError(cls, dec.retry_after_s,
                                    est_wait_s=dec.est_wait_s)
            if self._journal is not None:
                # WRITE-AHEAD for real: the admit record must land
                # before the request becomes visible to the engine
                # thread (registered below) — otherwise an emit batch
                # could flush ahead of its admit and replay would drop
                # the orphaned tokens. A scheduler refusal below
                # compensates with a tombstone.
                self._journal.note_admit(req, self.config_epoch)
            # register BEFORE scheduler.submit: the engine thread may
            # plan the rid immediately, and _do_prefill treats an
            # unknown rid as cancelled
            self._requests[rid] = req
            # trace BEFORE scheduler.submit (prefill_start on an
            # unknown rid would silently drop the span). config_epoch
            # attributes the trace to the engine config that admitted
            # it (a hot switch bumps the epoch, so traces spanning one
            # are distinguishable — cake_tpu/autotune). trace_id is
            # the originating x-cake-trace (front-door router /
            # client): the key the federated timeline correlates this
            # replica-local record under.
            self.tracer.admit(rid, len(ids), max_new, priority=cls,
                              config_epoch=self.config_epoch,
                              trace=trace_id)
            if (self._disagg is not None
                    and self._disagg.role == "decode"
                    and replay_tokens is None and not want_top_logprobs
                    and self._disagg.request_prefill(req)):
                # disaggregated front door: the admission is held OUT
                # of the scheduler while the prefill peer computes its
                # pages — disagg_complete enters it (with the shipment
                # to adopt, or without one after any channel failure).
                # Replays and top-logprob requests stay local: a replay
                # suffix already holds generated tokens, and the
                # shipped first token carries no top-N alternatives.
                # request_prefill == False means the channel is down —
                # fall through to the local path, same as colocated.
                req._disagg_pending = True
            else:
                ok = (self.scheduler.submit(rid, len(ids), max_new,
                                            priority=cls)
                      if self._slo else
                      self.scheduler.submit(rid, len(ids), max_new))
                if not ok:
                    self._requests.pop(rid, None)
                    self.tracer.drop(rid)
                    if self._journal is not None:
                        # the admit was journaled write-ahead; the
                        # refused admission must not replay after a
                        # restart
                        self._journal.note_retire(rid, "cancelled")
                    retry = 1.0
                    if self._shed is not None:
                        retry = self._shed.estimate_retry_after(
                            cls, self.scheduler.queue_depth)
                    raise QueueFullError(retry_after=retry)
            if idempotency_key is not None:
                with self._rid_lock:
                    self._idem_live[idempotency_key] = rid
        self._set_queue_gauges()
        self._wake.set()
        return RequestHandle(req, self.tokenizer, self.config.eos_token_ids)

    # -- disaggregated serving (cake_tpu/kv/transfer.py) -------------------

    def disagg_complete(self, rid: int, shipment) -> None:
        """Decode-plane channel thread: the peer's answer for a
        deferred admission arrived — a reassembled Shipment to adopt,
        or None (peer down / timeout / refused / corrupt), which means
        whole-prompt prefill locally. Either way the request NOW
        enters the scheduler; adoption itself happens on the engine
        thread when _do_prefill/_mixed_admit reach the rid."""
        with self._switch_lock:
            req = self._requests.get(rid)
            if req is None or not req._disagg_pending:
                return   # cancelled / failed while the shipment flew
            req._disagg_pending = False
            if shipment is not None:
                with self._rid_lock:
                    self._adopt_store[rid] = shipment
            ids, max_new = req.prompt_ids, req.max_new_tokens
            ok = (self.scheduler.submit(rid, len(ids), max_new,
                                        priority=req.priority)
                  if self._slo else
                  self.scheduler.submit(rid, len(ids), max_new))
            if not ok:
                # mirror submit's refusal compensation — the deferred
                # admission was already registered/journaled, so the
                # late refusal must finish the handle with the same
                # retryable error a synchronous refusal raises
                self._requests.pop(rid, None)
                with self._rid_lock:
                    self._adopt_store.pop(rid, None)
                self.tracer.drop(rid)
                if self._journal is not None:
                    self._journal.note_retire(rid, "cancelled")
                req.error = QueueFullError(retry_after=1.0)
                req.done.set()
                return
        self._set_queue_gauges()
        self._wake.set()

    # -- durable serving: idempotency, drain, journal seams --------------

    def _attach_idempotent(self, key: str,
                           stream=None) -> Optional[RequestHandle]:
        """A submit whose idempotency key matches a live or finished
        request attaches to THAT stream (safe client retry — across
        reconnects AND restarts, since the journal replay re-registers
        keys). The new stream callback replaces the dead client's;
        tokens the swap races are covered by the reconnect replay
        (api/server.py dedupes by absolute event id). None = no match
        (admit normally)."""
        with self._rid_lock:
            rid = self._idem_live.get(key)
            req = self._requests.get(rid) if rid is not None else None
            if req is None:
                req = self._idem_done.get(key)
            if req is None:
                return None
        if not req.done.is_set() and stream is not None:
            req.stream = stream
            req.stream_wants_count = bool(
                getattr(stream, "wants_count", False))
        h = RequestHandle(req, self.tokenizer, self.config.eos_token_ids)
        h.attached = True
        return h

    def seed_finished_idempotent(self, rec: dict) -> None:
        """Journal replay (serve/journal.recover): a request that
        COMPLETED before the crash but whose client may still retry —
        synthesize its finished state into the idempotency registry so
        the retry attaches to the transcript instead of re-running it.
        Errored/cancelled records are not seeded (a fresh retry is the
        right outcome for those)."""
        key = rec.get("idempotency_key")
        if not key or rec.get("error") \
                or rec.get("status") == "cancelled":
            return
        out = list(rec.get("out_tokens") or ())
        req = _Request(
            rid=int(rec.get("rid") or 0),
            prompt_ids=list(rec.get("prompt_ids") or ()),
            max_new_tokens=int(rec.get("max_new")
                               or rec.get("remaining") or 0),
            temperature=rec.get("temperature", 0.0),
            top_p=rec.get("top_p", 1.0),
            repeat_penalty=rec.get("repeat_penalty", 1.0),
            stream=None,
            priority=rec.get("priority", "standard"),
            idempotency_key=key,
            replayed_tokens=list(rec.get("replayed") or ()),
        )
        req.out_tokens = out
        # the journal stores no logprobs; a replayed transcript serves
        # text/ids only (documented limitation)
        req.out_logprobs = [0.0] * len(out)
        req.out_top = [[] for _ in out]
        req.done.set()
        with self._rid_lock:
            self._idem_done[key] = req
            while len(self._idem_done) > self._idem_done_cap:
                self._idem_done.popitem(last=False)

    def _journal_retire(self, req: _Request, status: str,
                        error: Optional[str] = None) -> None:
        """THE terminal side-channel shared by every retire seam
        (_emit finish, recovered-finish, force-finish, drop, fail-all,
        cancel, requeue-exhausted): write the journal tombstone and
        transition the idempotency registry — a completed keyed
        request stays attachable in the bounded done-ring, a
        failed/cancelled one frees its key so a retry re-runs."""
        if self._journal is not None:
            self._journal.note_retire(req.rid, status, error=error)
        key = req.idempotency_key
        if key is None:
            return
        with self._rid_lock:
            if self._idem_live.get(key) == req.rid:
                del self._idem_live[key]
            if status == "retired":
                self._idem_done[key] = req
                while len(self._idem_done) > self._idem_done_cap:
                    self._idem_done.popitem(last=False)

    def begin_drain(self) -> dict:
        """Close admissions (new submits raise the typed DrainingError
        the API maps to 429 + computed Retry-After) while in-flight
        work keeps decoding. POST /api/v1/drain and the SIGTERM paths
        call this before finishing/snapshotting and exiting clean."""
        if not self._draining:
            log.info("drain: admissions closed (%d in flight)",
                     len(self._requests))
        self._draining = True
        self._wake.set()
        return self.drain_state()

    def _drain_eta_s(self) -> float:
        """Computed seconds until the drain completes: remaining
        budgeted tokens over the measured decode rate (capped; a 1s
        floor matches the API's Retry-After ceil)."""
        remaining = sum(max(0, r.max_new_tokens - len(r.out_tokens))
                        for r in list(self._requests.values())
                        if not r.done.is_set())
        if remaining == 0:
            return 1.0
        rate = self.stats.decode_tokens_per_s
        if rate > 0:
            return min(600.0, max(1.0, remaining / rate))
        return min(600.0, max(1.0, remaining / 8.0))

    def drain_state(self) -> dict:
        """/api/v1/health `draining` block + the drain response."""
        pending = sum(1 for r in list(self._requests.values())
                      if not r.done.is_set())
        out = {"draining": self._draining,
               "pending_requests": pending,
               "queue_depth": self.queue_depth}
        if self._draining:
            out["eta_s"] = round(self._drain_eta_s(), 3)
        return out

    def register_prefix(self, prefix_ids: Sequence[int]) -> int:
        """Precompute and cache the KV of a shared prompt head (e.g. the
        rendered system prompt). Later submits whose prompt starts with
        these ids prefill only the suffix — prefill FLOPs and TTFT drop
        proportionally. Returns a prefix id (for unregister_prefix).

        HBM cost per prefix: L*P*KV*hd*2 entries in cache dtype (an
        8B-model 1k-token prefix is ~130 MiB at bf16; stage-sharded on a
        pipelined engine). Unavailable on ring (sliding-window) caches
        (see _prefix_capable). Multi-host: the coordinator publishes a
        register_prefix op and every follower computes the same prefix KV
        (the registration prefill is itself a cross-process collective,
        so it runs on the engine thread — wire position == dispatch
        position); followers reject direct registrations.
        """
        if self._multihost and self._control is None:
            raise ValueError(
                "followers mirror the coordinator's prefix registry; "
                "register prefixes on the coordinator process")
        if not self._prefix_capable:
            # name the ACTUAL refusal per engine flavor — the paged
            # engine serves prefixes now (page-granular sharing), so a
            # one-size message would blame the wrong subsystem
            if self._spec:
                reason = ("speculative serving keeps the draft cache "
                          "aligned with the target, and a prefix-cached "
                          "target prefill would leave the draft cold "
                          "(acceptance would silently collapse)")
            elif self.ring:
                reason = ("ring sliding-window caches own their layout "
                          "(a prefix install writes dense positions the "
                          "ring would misplace)")
            else:
                reason = ("these custom step fns provide no "
                          "chunked-prefill variant to window the suffix "
                          "at the prefix boundary")
            raise ValueError(f"prefix caching is unavailable here: "
                             f"{reason}")
        ids = list(prefix_ids)
        if not ids:
            raise ValueError("empty prefix")
        if len(ids) >= self.max_seq_len - 1:
            raise ValueError(
                f"prefix length {len(ids)} leaves no room for a suffix "
                f"(max_seq_len {self.max_seq_len})")
        if self.paged:
            with self._switch_lock:
                # a live reconfigure swaps the pager wholesale; the
                # switch lock pins one consistent page size for this
                # validation (same discipline as submit's pool bound)
                P = self._pager.page_size
            if len(ids) < P:
                raise ValueError(
                    f"paged prefix sharing is page-granular: the prefix "
                    f"({len(ids)} tokens) is shorter than one kv page "
                    f"({P} tokens), so there is nothing to share "
                    "(lower --kv-page-size or skip registration)")
            # pool pages + the table are single-writer state: route
            # through the engine thread when it is running (auto-prefix
            # registrations arrive on HTTP handler threads)
            if self._thread is not None and self._thread.is_alive():
                return self._run_on_engine_thread(
                    lambda: self._register_prefix_paged(ids))
            # cakelint: skip[affinity] pre-start direct drive: no engine thread exists to race; the runtime assert enforces this
            return self._register_prefix_paged(ids)
        if self._control is not None:
            return self._run_on_engine_thread(
                lambda: self._register_prefix_sync(ids))
        return self._register_prefix_sync(ids)

    def _register_prefix_sync(self, ids: List[int]) -> int:
        """Allocate a pid, publish (multi-host), compute the prefix KV on
        device, store. Coordinator-side; followers mirror via the
        register_prefix op handler."""
        P = len(ids)
        bucket = bucket_length(P, self.max_seq_len)
        with self._rid_lock:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
        self._publish({"op": "register_prefix", "ids": ids, "pid": pid})
        k, v = self._prefix_kv_device(ids, P, bucket)
        with self._rid_lock:
            self._prefixes[pid] = (ids, k, v)
        log.info("registered prefix %d: %d tokens", pid, P)
        return pid

    @engine_thread_only
    def _register_prefix_paged(self, ids: List[int]) -> int:
        """Paged registration: round the prefix DOWN to a page boundary
        (remainder ids join every request's suffix — no copy-on-write of
        a partial last page), prefill it ONCE into dedicated pool pages,
        and record the page list. Matching admissions map those pages
        read-only into their table rows (_alloc_slot_pages) — a 1k-token
        system prompt costs ceil(1k/page) pool pages TOTAL instead of
        per slot. Runs on the engine thread when the engine is live (the
        pool + table are single-writer state)."""
        P = self._pager.page_size
        aligned = (len(ids) // P) * P
        p_ids = ids[:aligned]
        n_pp = aligned // P
        pages = self._pager.alloc(aligned)
        if pages is None:
            raise ValueError(
                f"kv page pool cannot hold the prefix: needs {n_pp} "
                f"pages, {self._pager.free_pages} free (raise "
                "--kv-pages, or register before taking load)")
        with self._rid_lock:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
        self._prefix_last_hit[pid] = time.monotonic()
        row = np.full(self.cache.max_pages, -1, np.int64)
        row[:n_pp] = pages
        try:
            fargs = (self.params, jnp.asarray([p_ids], jnp.int32),
                     jnp.asarray(row, jnp.int32), self.cache, self.rope,
                     self.config)
            js = self._obs_jit("prefill_prefix_pages", (aligned,),
                               self._prefix_pages_step, fargs)
            t0 = time.perf_counter()
            self.cache = self._prefix_pages_step(*fargs)
            js.finish(time.perf_counter() - t0)
        except Exception:
            self._pager.release(pages)
            raise
        with self._rid_lock:
            self._prefixes[pid] = (p_ids, pages, None)
        log.info("registered paged prefix %d: %d tokens in %d shared "
                 "pages (%d trailing tokens fall to each suffix)",
                 pid, aligned, n_pp, len(ids) - aligned)
        return pid

    def _prefix_kv_device(self, ids: List[int], P: int, bucket: int):
        """Device computation of a prefix's KV (identical on every
        process: a multi-host follower replays this as one collective)."""
        padded = ids + [0] * (bucket - P)
        if self._prefill_slot is prefill_slot:
            tmp = KVCache.create(self.config, 1, bucket,
                                 dtype=self._cache_dtype)
            from cake_tpu.models.llama.model import prefill
            _, tmp = prefill(self.params,
                             jnp.asarray([padded], jnp.int32),
                             jnp.asarray([P], jnp.int32),
                             tmp, self.rope, self.config)
        else:
            # pipelined path: prefill slot 0 of a one-slot TEMP cache
            # with the serving cache's sharding — the prefix k/v stay
            # stage-sharded, matching the install target
            tmp = self._sharded_like_cache(1, bucket)
            _, tmp = self._prefill_slot(
                self.params, jnp.asarray([padded], jnp.int32),
                jnp.asarray([P], jnp.int32), jnp.int32(0), tmp,
                self.rope, self.config)
        k = jax.lax.slice_in_dim(tmp.k, 0, P, axis=2)
        v = jax.lax.slice_in_dim(tmp.v, 0, P, axis=2)
        return k, v

    def _run_on_engine_thread(self, fn, timeout: float = 300.0):
        """Execute fn on the engine thread between iterations and return
        its result. Multi-host prefix ops MUST run there: they dispatch
        cross-process collectives, and only the engine thread's program
        order matches the control channel's op order (a handler-thread
        dispatch could interleave with a step op differently on the
        coordinator than on a follower, wedging the mesh)."""
        if self._thread is None or not self._thread.is_alive():
            raise RuntimeError(
                "engine not running: multi-host prefix operations "
                "execute on the engine thread (start() first)")
        box: dict = {}
        ev = threading.Event()
        with self._rid_lock:
            self._cmd_q.append((fn, box, ev))
        self._wake.set()
        if not ev.wait(timeout):
            raise TimeoutError("engine thread did not run the command "
                               f"within {timeout:.0f}s")
        if "error" in box:
            raise box["error"]
        return box["result"]

    @engine_thread_only
    def _drain_commands(self) -> None:
        with self._rid_lock:
            cmds, self._cmd_q = self._cmd_q, []
        for fn, box, ev in cmds:
            try:
                box["result"] = fn()
            except Exception as e:  # noqa: BLE001
                box["error"] = e
            finally:
                ev.set()

    def _fail_pending_commands(self) -> None:
        """Engine exit: release command waiters instead of letting them
        time out against a dead thread."""
        with self._rid_lock:
            cmds, self._cmd_q = self._cmd_q, []
        for _fn, box, ev in cmds:
            box["error"] = RuntimeError("engine stopped")
            ev.set()

    def _sharded_like_cache(self, slots: int, length: int) -> KVCache:
        """Zeroed [L, slots, length] cache with the serving cache's
        sharding (stage/tp axes preserved, batch/seq unsharded dims
        free to differ)."""
        make = jax.jit(
            lambda: KVCache.create(self.config, slots, length,
                                   dtype=self._cache_dtype),
            out_shardings=self._cache_shardings)
        return make()

    def unregister_prefix(self, prefix_id: int) -> None:
        if (self._control is not None and self._thread is not None
                and self._thread.is_alive()):
            # engine-thread ordering guarantees no later prefill_prefixed
            # op on the wire references the dropped pid (matching happens
            # on the same thread, after this pop)
            def job():
                self._publish({"op": "unregister_prefix",
                               "pid": prefix_id})
                with self._rid_lock:
                    self._prefixes.pop(prefix_id, None)
            self._run_on_engine_thread(job)
            return
        if self.paged and (self._thread is not None
                           and self._thread.is_alive()):
            # the registry's page references drop on the engine thread:
            # slots mid-decode on those pages hold their own refs, so
            # the pages outlive the registration until the last request
            # using them retires
            self._run_on_engine_thread(
                lambda: self._unregister_paged_sync(prefix_id))
            return
        if self.paged:
            # cakelint: skip[affinity] reached only with the engine thread not running (checked above); runtime assert backstops
            self._unregister_paged_sync(prefix_id)
            return
        with self._rid_lock:
            self._prefixes.pop(prefix_id, None)

    @engine_thread_only
    def _unregister_paged_sync(self, prefix_id: int) -> None:
        with self._rid_lock:
            entry = self._prefixes.pop(prefix_id, None)
        self._prefix_last_hit.pop(prefix_id, None)
        if entry is not None:
            if entry[1] is not None:
                self._pager.release(entry[1])
            elif self._host_tier is not None:
                # spilled registration: the pages live in the host
                # tier, not the pool — drop the host copy instead
                self._host_tier.drop(("prefix", prefix_id))

    def _match_prefix(self, ids: List[int]):
        """Longest registered prefix that is a proper head of `ids`:
        (pid, p_ids, k, v) or None."""
        best = None
        with self._rid_lock:
            entries = list(self._prefixes.items())
        for pid, (p_ids, k, v) in entries:
            P = len(p_ids)
            if P < len(ids) and ids[:P] == p_ids:
                if best is None or P > len(best[1]):
                    best = (pid, p_ids, k, v)
        return best

    def chat(self, messages: Sequence[Message], **kw) -> RequestHandle:
        """Render a chat history through the Llama-3 template and submit.

        With auto_prefix_system on, the system message's rendered head is
        KV-cached once per distinct system prompt, so every conversation
        sharing it prefills only its own turns."""
        hist = History(self.config.chat_template)
        for m in messages:
            hist.add_message(m)
        if (self._auto_prefix and messages
                and messages[0].role.value == "system"
                and self._prefix_capable
                and (not self._multihost or self._control is not None)
                and hist.template == "llama3"):
            # the head builder below renders the llama3 system block;
            # other templates (mistral merges system into the first user
            # turn) have no standalone shared head
            self._auto_register_system(messages[0])
        return self.submit(encode_text(self.tokenizer, hist.render()), **kw)

    def _auto_register_system(self, system_msg: Message) -> None:
        from cake_tpu.models.chat import BEGIN_OF_TEXT
        head = BEGIN_OF_TEXT + History.encode_message(system_msg)
        evict = None
        with self._rid_lock:
            if head in self._auto_pids:
                pid = self._auto_pids[head]
                if pid is None or pid < 0 or pid in self._prefixes:
                    return   # in-flight, negative-cached, or live
                # stale head->pid: the registry was cleared underneath
                # a completed registration (paged _reset_after_error
                # racing a handler-thread registration) — drop the
                # entry and re-register, or this head would silently
                # serve whole-prompt prefills forever
                del self._auto_pids[head]
            if len(self._auto_pids) >= self._max_auto:
                # evict the oldest COMPLETED registration; in-flight
                # reservations (None) are skipped
                for k, pid in list(self._auto_pids.items()):
                    if pid is not None:
                        del self._auto_pids[k]
                        evict = pid
                        break
                else:
                    return    # registry full of in-flight reservations
            self._auto_pids[head] = None   # reserve before the prefill
        if evict is not None and evict >= 0:
            # through unregister_prefix, OUTSIDE the lock: under
            # multi-host it publishes the eviction to followers (a direct
            # pop would leak the prefix KV in every follower's mirrored
            # registry) and routes through the engine thread, which may
            # itself need _rid_lock
            try:
                self.unregister_prefix(evict)
            except Exception:  # noqa: BLE001
                log.exception("auto-prefix eviction failed")
        try:
            ids = encode_text(self.tokenizer, head)
            min_len = 8
            if self.paged:
                # page-granular sharing: a head shorter than one page
                # has nothing to share (register_prefix would refuse)
                with self._switch_lock:
                    min_len = max(min_len, self._pager.page_size)
            if len(ids) < min_len or len(ids) >= self.max_seq_len - 1:
                # unqualifying head: keep a negative sentinel so the
                # membership check short-circuits every later request
                # with the same system prompt
                with self._rid_lock:
                    self._auto_pids[head] = -1
                return
            pid = self.register_prefix(ids)
        except Exception:
            # cache warming must never fail the request — drop the
            # reservation and let the normal whole-prompt prefill serve it
            log.exception("auto prefix registration failed; serving "
                          "without prefix cache")
            with self._rid_lock:
                self._auto_pids.pop(head, None)
        else:
            with self._rid_lock:
                self._auto_pids[head] = pid

    def cancel(self, handle: RequestHandle) -> None:
        """Abandon a request (e.g. the streaming client disconnected):
        its slot frees for the next queued request instead of decoding to
        max_new_tokens for nobody. Safe from any thread; the engine
        thread performs the actual teardown — unless it has already
        exited (shutdown window), in which case teardown runs inline so
        the request can neither hang wait() nor be checkpointed as live."""
        with self._rid_lock:
            self._cancel_q.append(handle._req.rid)
        self._wake.set()
        if self._stop.is_set() and (self._thread is None
                                    or not self._thread.is_alive()):
            # cakelint: skip[affinity] shutdown window: the engine thread has exited (checked above); runtime assert backstops
            self._drain_cancellations()

    def _host_attention_pending(self) -> bool:
        """Something on the host side needs the run loop back: stop,
        admissions waiting, cancellations, or commands."""
        return (self._stop.is_set()
                or self.scheduler.queue_depth > 0
                or self._cancel_pending()
                or self._commands_pending())

    def _drive_burst(self, dispatch, complete, can_chain,
                     first_unconditional: bool = False) -> None:
        """THE double-buffered dispatch/fetch driver, shared by the
        decode burst and the speculative burst: dispatch k+1 (chained
        from k's on-device state, zero host round-trips between
        dispatches) BEFORE completing k, so the ~100ms d2h fetch
        latency of a remote-dispatch tunnel hides under k+1's device
        compute.

        dispatch(state) -> (devs, state'): device dispatch, no fetch.
        complete(devs): fetch + emit one dispatch's results.
        can_chain(n_inflight) -> bool: burst-specific budget/window
        gating (called after the shared host-attention gate);
        n_inflight = dispatched-but-unfetched count, for projecting
        the device frontier past the stale host mirrors.
        first_unconditional: guarantee one dispatch per call even when
        the gates say stop — a caller whose planning loop has no other
        progress path would otherwise spin forever (the spec burst with
        full slots and a waiting queue)."""
        inflight: list = []
        state = None
        first = first_unconditional
        while True:
            chain = first or (not self._host_attention_pending()
                              and can_chain(len(inflight)))
            first = False
            if chain:
                devs, state = dispatch(state)
                inflight.append(devs)
            if not inflight:
                break
            if not chain or len(inflight) >= 2:
                complete(inflight.pop(0))

    def _cancel_pending(self) -> bool:
        with self._rid_lock:
            return bool(self._cancel_q)

    def _commands_pending(self) -> bool:
        with self._rid_lock:
            return bool(self._cmd_q)

    @engine_thread_only
    def _drain_cancellations(self) -> None:
        with self._rid_lock:
            rids, self._cancel_q = self._cancel_q, []
        for rid in rids:
            req = self._requests.pop(rid, None)
            if req is None:
                continue
            self.scheduler.cancel(rid)
            with self._rid_lock:
                # a shipment staged for a cancelled admission must not
                # outlive it in the adoption store
                self._adopt_store.pop(rid, None)
            if self._host_tier is not None:
                # a victim cancelled while parked leaves its spilled
                # pages orphaned in the LRU — drop them now
                self._host_tier.drop(("victim", rid))
            if req.slot >= 0 and self._slot_req[req.slot] is req:
                self._slot_req[req.slot] = None
                self._release_slot_pages(req.slot)
            req.finish_t = time.perf_counter()
            self._journal_retire(req, "cancelled")
            self.tracer.finish(rid, "cancelled",
                               output_tokens=len(req.out_tokens))
            req.done.set()

    @property
    def queue_depth(self) -> int:
        return self.scheduler.queue_depth

    @property
    def active(self) -> int:
        return self.scheduler.active

    # -- engine loop ----------------------------------------------------------

    def _run(self) -> None:
        try:
            self._run_loop()
        finally:
            # cancellations enqueued in the stop window must still tear
            # down (an undrained handle would block wait() forever and be
            # replayed as live by a checkpoint snapshot); command waiters
            # get an error instead of a timeout
            self._drain_cancellations()
            self._fail_pending_commands()

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            self._drain_cancellations()
            self._drain_commands()
            if self._autotuner is not None:
                # between iterations only — a switch folds every slot,
                # so it must never land mid-wave (the preemption
                # invariant); the tick itself is a no-op off-interval
                self._autotune_tick()
            if self._slo and self._preemption:
                # between iterations only: no device work is in flight,
                # so a reclaimed slot cannot be mid-decode through a
                # just-released page-table row
                self._maybe_preempt()
            prefill_plan, decode_plan = self.scheduler.plan()
            # decode-resident slots THIS iteration: the candidate set
            # for _spill_resident_stream — plan()'s decode rows only,
            # never same-wave admissions (their prefill may be in
            # flight when an admission later in the wave spills)
            self._resident_parked = False
            self._cur_decode = {s: r for r, s in decode_plan}
            if self._slo:
                self._set_queue_gauges()
            if not prefill_plan and not decode_plan:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            if getattr(self, "_page_starved", False):
                # a page-starved prefill was requeued last iteration; if
                # nothing can retire pages this round (no decode work),
                # back off instead of spin-planning the same admission
                self._page_starved = False
                if not decode_plan:
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
            try:
                if self._faults is not None:
                    # chaos plane, top-of-iteration site (step= triggers
                    # key off the engine step counter)
                    self._faults.check("engine.step",
                                       step=self.stats.steps)
                if self._mixed:
                    self._do_mixed(prefill_plan, decode_plan)
                elif prefill_plan and not self._multihost:
                    self._do_prefill_batch(prefill_plan)
                else:
                    for rid, slot in prefill_plan:
                        self._do_prefill(rid, slot)
                if decode_plan and not self._mixed:
                    if self._resident_parked:
                        # an admission above parked a decode-resident
                        # slot: the plan predates the park, and the
                        # device step must not write through a
                        # released page-table row
                        decode_plan = self._live_decode_rows(decode_plan)
                if decode_plan and not self._mixed:
                    if self._spec:
                        self._do_decode_spec(decode_plan)
                    else:
                        n = self._scan_steps_for(decode_plan)
                        if n > 1 and not self._multihost:
                            self._decode_burst(decode_plan, n)
                        elif n > 1:
                            self._do_decode_scan(decode_plan, n)
                        else:
                            self._do_decode(decode_plan)
                if getattr(self, "_fail_recs", None) is not None:
                    # a successful iteration (real device work incl.
                    # collectives) proves the mesh recovered: the
                    # earlier failure was genuinely transient, so its
                    # capture must not resurrect already-errored
                    # requests in a later fatal's snapshot
                    self._fail_recs = None
                if self._consec_resets:
                    # a successful iteration ends the reset episode:
                    # the next failure backs off from scratch
                    self._consec_resets = 0
                # the iteration's dispatches all landed: a failure in
                # the NEXT iteration before any dispatch (engine.step
                # site, planning/admission code) must implicate nobody
                # — not this iteration's requests
                self._implicated = ()
                if self._journal is not None:
                    # one emit record per request touched this
                    # iteration (+ the batch-mode fsync barrier), then
                    # the size-triggered compaction check — both here,
                    # between iterations, where the registry is stable
                    self._journal.flush()
                    self._journal.maybe_compact(self)
            except Exception as e:  # noqa: BLE001
                log.exception("engine iteration failed")
                # capture the request records FIRST (cheap, pure
                # Python — the reset publish below can block for
                # minutes against a network-partitioned follower's
                # full TCP buffer), and only if the failure proves
                # fatal write them as the pre-fail snapshot. Transient
                # reset-and-continue errors write nothing: a stale
                # snapshot would resurrect long-errored requests after
                # a later unclean exit.
                recs = None
                if getattr(self, "snapshot_path", None):
                    from cake_tpu.serve import checkpoint
                    recs = checkpoint.snapshot_requests(self)
                    # stash for the heartbeat monitor: a dead follower
                    # often looks transient HERE (the reset publish can
                    # land in the dead peer's TCP buffer) and only the
                    # heartbeat loss seconds later proves it fatal — by
                    # then the registry is empty, so the monitor's
                    # snapshot falls back to this capture
                    self._fail_recs = (time.monotonic(), recs)
                if not self._continue_after_failure(e, recs):
                    return

    # -- crash recovery (cake_tpu/faults + the fail-everything fix) ------

    def _note_reset(self) -> bool:
        """Record one reset in the storm window; True = the breaker
        trips (too many resets in storm_window_s: the fault is not
        transient, stop cleanly instead of thrashing)."""
        cfg = self._recovery_cfg
        now = time.monotonic()
        self._reset_times.append(now)
        cut = now - cfg.storm_window_s
        while self._reset_times and self._reset_times[0] < cut:
            self._reset_times.pop(0)
        return (self._recover
                and len(self._reset_times) >= cfg.storm_resets)

    def _continue_after_failure(self, e: Exception, recs) -> bool:
        """Post-failure policy: transparent recovery (reset + resubmit
        the in-flight requests), or the legacy fail-everything path
        (recovery off / flavor without the fold), or — on a reset
        storm — breaker-open snapshot + clean stop. Returns False when
        the engine must stop."""
        from cake_tpu.serve.errors import as_engine_error
        storm = self._note_reset()
        if self._recover and not storm and not self._stop.is_set():
            return self._attempt_recovery(e, recs)
        err = as_engine_error(e)
        if storm:
            log.error("reset storm: %d resets within %.0fs — breaker "
                      "open; snapshotting in-flight requests and "
                      "stopping cleanly", len(self._reset_times),
                      self._recovery_cfg.storm_window_s)
            self._breaker_tripped = True
            _RECOVERIES.labels(outcome="storm_breaker").inc()
            self.stats.errors += 1
            self.stats.last_error = f"{type(e).__name__}: {e}"
            return self._stop_with_snapshot(recs, err,
                                            trigger="breaker_stop")
        # legacy fail-everything: release the waiters FIRST (the reset
        # publish can block for minutes against a network-partitioned
        # follower's full TCP buffer), then prove the mesh is still
        # drivable, then rebuild
        self._fail_all(err)
        fatal = False
        try:
            self._publish({"op": "reset"})
        except Exception:  # noqa: BLE001
            # followers unreachable: the SPMD mesh is no longer fully
            # driven — stop serving instead of hanging the next
            # collective
            log.exception("control publish failed; stopping")
            fatal = True
        if fatal:
            return self._stop_with_snapshot(recs,
                                            trigger="control_lost")
        try:
            self._reset_after_error()
        except Exception:  # noqa: BLE001
            # the rebuild itself failed (OOM rebuilding the cache, a
            # dead device): the engine cannot serve again — snapshot
            # what the first failure captured and stop CLEANLY,
            # instead of the raise silently killing the thread with no
            # checkpoint and no metric (the API would 200 /health
            # while every request hangs in the queue forever)
            log.exception("post-error engine reset failed; "
                          "stopping the engine")
            _RESET_FAILURES.inc()
            self.stats.errors += 1
            self.stats.last_error = "reset failed"
            return self._stop_with_snapshot(recs,
                                            trigger="reset_failed")
        self.stats.errors += 1
        self.stats.last_error = f"{type(e).__name__}: {e}"
        return True

    def _stop_with_snapshot(self, recs,
                            err: Optional[Exception] = None,
                            trigger: str = "engine_stop") -> bool:
        """The unrecoverable-failure tail shared by every stop branch:
        fail any still-waiting clients FIRST (omitted when the caller
        already released them), persist the pre-fail capture, dump the
        black-box postmortem bundle (--postmortem-dir; `trigger` names
        the terminal cause), stop the engine thread. Always returns
        False — the _continue_after_failure 'engine must stop'
        contract — so callers can
        `return self._stop_with_snapshot(...)`."""
        if err is not None:
            self._fail_all(err)
        # best-effort stop op: a breaker/reset-failed stop leaves this
        # PROCESS alive (the API keeps serving 503s, heartbeats keep
        # answering), so followers would otherwise wait forever on a
        # healthy channel that carries no more ops — their liveness
        # deadline cannot see an engine-only death. Safe to publish
        # here: this runs on the engine thread just before its loop
        # exits, so no step op can follow it on the wire.
        try:
            self._publish({"op": "stop"})
        except Exception:  # noqa: BLE001
            log.warning("control: stop publish failed (followers will "
                        "exit on channel close)")
        with self._ckpt_lock:
            self._snapshot_before_fail(requests=recs)
        if self._postmortem is not None:
            # terminal: always leaves a bundle, even right after an
            # interval-bounded poison dump
            self._postmortem.dump(
                trigger, engine=self,
                reason=str(err) if err is not None
                else self.stats.last_error, force=True)
        self._stop.set()
        return False

    def _attempt_recovery(self, e: Exception, recs) -> bool:
        """The fail-everything replacement: implicate the failing
        dispatch's requests, publish the reset (multi-host followers
        replay it so the SPMD programs line up), back off if resets
        are consecutive, rebuild device state, then RESUBMIT every
        surviving request through the checkpoint fold-tokens-into-
        prompt path — greedy streams complete token-identical across
        the crash. Returns False when the engine must stop."""
        from cake_tpu.serve.errors import as_engine_error
        t0 = time.perf_counter()
        implicated = [rid for rid, _slot in self._implicated]
        self._implicated = ()
        for rid in implicated:
            req = self._requests.get(rid)
            if req is not None and not req.done.is_set():
                req.crash_count += 1
        try:
            self._publish({"op": "reset"})
        except Exception:  # noqa: BLE001
            log.exception("control publish failed; stopping")
            return self._stop_with_snapshot(recs, as_engine_error(e),
                                            trigger="control_lost")
        # exponential backoff between CONSECUTIVE resets (the first is
        # immediate): a persistent fault must not spin the engine
        # thread through rebuild loops at full speed. Interruptible —
        # a stop() during the wait still tears down promptly.
        cfg = self._recovery_cfg
        self._consec_resets += 1
        if self._consec_resets > 1:
            delay = min(cfg.backoff_cap_s,
                        cfg.backoff_base_s
                        * (2.0 ** (self._consec_resets - 2)))
            log.warning("recovery: consecutive reset #%d, backing off "
                        "%.2fs", self._consec_resets, delay)
            if self._stop.wait(delay):
                self._fail_all(as_engine_error(e))
                return False
        try:
            self._reset_after_error()
        except Exception:  # noqa: BLE001
            log.exception("post-error engine reset failed; "
                          "stopping the engine")
            _RESET_FAILURES.inc()
            _RECOVERIES.labels(outcome="reset_failed").inc()
            self.stats.errors += 1
            self.stats.last_error = "reset failed"
            return self._stop_with_snapshot(recs, as_engine_error(e),
                                            trigger="reset_failed")
        n_rec, n_poison = self._resubmit_after_reset(e)
        self.stats.errors += 1
        self.stats.last_error = f"{type(e).__name__}: {e}"
        self.stats.recoveries += 1
        dt = time.perf_counter() - t0
        _RECOVERY_SECONDS.observe(dt)
        if len(self.recovery_seconds) < 512:
            self.recovery_seconds.append(dt)
        _RECOVERIES.labels(outcome="recovered").inc()
        log.warning("recovered from step failure (%s: %s): %d "
                    "request(s) resubmitted, %d quarantined, %.3fs",
                    type(e).__name__, e, n_rec, n_poison, dt)
        self._wake.set()
        return True

    def _resubmit_after_reset(self, cause: Exception):
        """Rebuild the request-side bookkeeping after a reset:
        quarantine poison requests (implicated in implication_budget
        consecutive failed steps), requeue everyone else with their
        generated tokens folded into the prompt — priority class,
        seniority (SLO requeue) and preempt budget all survive because
        the SAME _Request object is resubmitted. Engine thread only.
        Returns (resubmitted, quarantined) counts."""
        from cake_tpu.serve.errors import (
            PoisonRequestError, as_engine_error,
        )
        cfg = self._recovery_cfg
        cause_s = f"{type(cause).__name__}: {cause}"
        # every slot mapping died with the rebuilt cache (the paged
        # reset already rebuilt pager/table/pending; dense slots are
        # only this list)
        self._slot_req = [None] * self.max_slots
        self._page_blocked_rid = None
        self._pending_page_preempt = None
        self._cur_decode = {}
        if not self.paged:
            self._mixed_pending.clear()
        n_rec = n_poison = 0
        for rid, req in sorted(self._requests.items()):
            if req.done.is_set():
                continue
            req.slot = -1
            req._kv_restored = False
            if req.crash_count >= cfg.implication_budget:
                self._drop_request(
                    req, PoisonRequestError(rid, req.crash_count,
                                            cause_s),
                    poison_reason="implicated")
                n_poison += 1
                continue
            remaining = req.max_new_tokens - len(req.out_tokens)
            if remaining <= 0:
                # was retiring in the failed step — it already holds
                # every token it asked for; finish it normally
                self._finish_recovered(req)
                n_rec += 1
                continue
            n_tok = len(req.prompt_ids) + len(req.out_tokens)
            if self._slo:
                # requeue preserves the original enqueue time
                # (seniority) and the preemption count; False just
                # means the request was still QUEUED — nothing to do
                self.scheduler.requeue(rid, n_tok, remaining)
                ok = True
            else:
                # FIFO scheduler has no requeue: cancel + resubmit in
                # rid order restores the original arrival order
                self.scheduler.cancel(rid)
                ok = self.scheduler.submit(rid, n_tok, remaining)
            if not ok:
                self._drop_request(req, as_engine_error(cause),
                                   poison_reason="resubmit_failed")
                n_poison += 1
                continue
            self.tracer.span(rid, "crash_recovered",
                             generated=len(req.out_tokens),
                             crashes=req.crash_count)
            if self.events is not None:
                self.events.publish("recovered", rid=rid,
                                    generated=len(req.out_tokens),
                                    crashes=req.crash_count)
            _RECOVERED_REQUESTS.inc()
            self.stats.requests_recovered += 1
            n_rec += 1
        return n_rec, n_poison

    def _drop_request(self, req: _Request, err: Exception,
                      poison_reason: Optional[str] = None) -> None:
        """Fail ONE request with a typed error during recovery
        (quarantine / resubmit failure) — the per-request sibling of
        _fail_all's teardown. Engine thread only; slots were already
        cleared by the reset."""
        req.error = err
        self.scheduler.cancel(req.rid)
        if self._host_tier is not None:
            self._host_tier.drop(("victim", req.rid))
        self._requests.pop(req.rid, None)
        self._journal_retire(req, "error", error=str(err))
        if poison_reason is not None:
            self.stats.poisoned += 1
            _POISON_REQUESTS.labels(reason=poison_reason).inc()
            if self.events is not None:
                self.events.publish("poisoned", rid=req.rid,
                                    reason=poison_reason,
                                    crashes=req.crash_count)
            log.error("quarantined rid=%d as poison (%s): %s",
                      req.rid, poison_reason, err)
            if self._postmortem is not None:
                # interval-bounded (not forced): a multi-request
                # quarantine cascade leaves ONE bundle, not one per rid
                self._postmortem.dump(
                    "poison", engine=self,
                    reason=f"rid={req.rid} {poison_reason}: {err}")
        self.tracer.finish(req.rid, "error", error=str(err),
                           output_tokens=len(req.out_tokens))
        req.done.set()

    def _finish_recovered(self, req: _Request) -> None:
        """Retire a request whose budget was already exhausted when
        the step failed: it has every token it asked for — deliver the
        final delta instead of resubmitting a zero-budget prefill."""
        if req.stream is not None:
            delta = self._incremental_text(req, final=True)
            try:
                if req.stream_wants_count:
                    req.stream(delta, True, len(req.out_tokens))
                else:
                    req.stream(delta, True)
            except Exception:  # noqa: BLE001
                log.exception("stream callback failed rid=%d", req.rid)
        req.finish_t = time.perf_counter()
        self.scheduler.cancel(req.rid)
        self._requests.pop(req.rid, None)
        self.stats.requests_completed += 1
        if self._shed is not None:
            # a retirement like any other: the shed controller's
            # measured service rate must count it, or post-recovery
            # Retry-After estimates inflate
            self._shed.observe_retire()
        self._journal_retire(req, "retired")
        self.tracer.finish(req.rid, "retired",
                           output_tokens=len(req.out_tokens))
        req.done.set()

    def recovery_state(self) -> dict:
        """Recovery/breaker introspection for /api/v1/health."""
        cfg = self._recovery_cfg
        out = {
            "enabled": self._recover,
            "recoveries": self.stats.recoveries,
            "requests_recovered": self.stats.requests_recovered,
            "poisoned": self.stats.poisoned,
            "consecutive_resets": self._consec_resets,
            "breaker": {
                "tripped": self._breaker_tripped,
                "resets_in_window": len(self._reset_times),
                "storm_resets": cfg.storm_resets,
                "window_s": cfg.storm_window_s,
            },
        }
        if self._faults is not None:
            out["fault_plan"] = self._faults.describe()
        if self._control is not None and hasattr(self._control,
                                                 "wire_state"):
            # control-plane wire state (published seq, per-follower
            # last-sent + last-acked seqs): a follower disconnect is
            # diagnosable from the health endpoint post-mortem
            out["control"] = self._control.wire_state()
        return out

    # -- per-request explain (obs/timeline.py) ---------------------------

    def request_timeline(self, rid: int) -> Optional[dict]:
        """GET /api/v1/requests/{rid}/timeline: one merged,
        time-ordered view of the request's trace spans, its event-bus
        events and the step records whose batch contained it — the
        single call that attributes a slow TTFT to its actual causes
        (preempted twice, prefix spilled then restored, folded by a
        config switch, ...). None when the rid is unknown (fell out of
        the finished ring, or never admitted) — the API's 404."""
        from cake_tpu.obs.timeline import build_timeline
        trace = self.tracer.get(rid)
        if trace is None:
            return None
        events = (self.events.dump(rid=rid)
                  if self.events is not None else [])
        local_host = None
        if self.telemetry is not None:
            # fleet-scope explain: the collector's remote events carry
            # their origin host and clock-offset-corrected timestamps,
            # so a request that prefilled on host A and decoded on
            # host B still reads as ONE ordered chronology
            local_host = getattr(self.telemetry, "local_host", None)
            try:
                events = events + self.telemetry.events_for(rid=rid)
            except Exception:  # noqa: BLE001 — explain must not fail
                log.debug("remote event merge failed", exc_info=True)
        return build_timeline(trace, events,
                              self.flight.records_for(rid),
                              local_host=local_host)

    # -- live reconfiguration (cake_tpu/autotune) ------------------------

    def _setup_paged_exec(self, kv_pages: int, kv_page_size: int,
                          paged_attn: Optional[str],
                          kv_host_pages: Optional[int]) -> None:
        """Build the paged execution state — step-fn partials, page
        allocator, pool cache, host tier — from the geometry knobs.
        The SINGLE source for __init__ AND the live hot-switch seam
        (_apply_exec_config): a reconfigured pool must resolve exactly
        as a startup one would. Requires self.paged/self.kv_quant/
        self._kv_dtype_name/self._base_cache_dtype already set."""
        from cake_tpu.models.llama.paged import (
            PageAllocator, PagedKVCache, decode_step_ragged_paged,
            mixed_step_paged, prefill_prefix_pages,
            prefill_slot_paged, prefill_slot_paged_chunk,
            prefill_slot_paged_prefixed,
        )
        if kv_pages < 1 or kv_page_size < 1:
            raise ValueError(
                f"--kv-pages {kv_pages} / --kv-page-size "
                f"{kv_page_size} must be >= 1")
        # paged_attn: {fold,pallas} attention impl for the paged step
        # fns; None/"auto" resolves via the ONE shared rule
        # (autotune/space.resolve_paged_attn — the autotuner's config
        # comparison key must never resolve "auto" differently from
        # this dispatch setup). The choice rides the jitted steps as a
        # STATIC arg, so both variants keep the same traced signature
        # and the engine's dispatch plumbing is impl-blind.
        from cake_tpu.autotune.space import resolve_paged_attn
        impl = resolve_paged_attn(paged_attn)
        if impl not in ("fold", "pallas"):
            raise ValueError(
                f"--paged-attn must be fold or pallas, got {impl!r}")
        self.paged_attn = impl
        self._prefill_slot = partial(prefill_slot_paged, attn=impl)
        self._decode_step = partial(decode_step_ragged_paged, attn=impl)
        self._decode_scan_impl = (_decode_scan_paged if impl == "fold"
                                  else _decode_scan_paged_pallas)
        # chunked paged prefill: long prompts admit in C-token windows
        self._prefill_chunk_step = partial(prefill_slot_paged_chunk,
                                           attn=impl)
        # page-granular prefix sharing: registered prefixes (and
        # auto_prefix_system heads) prefill ONCE into pool pages and
        # are mapped read-only into every matching slot's table row
        # (_alloc_slot_pages). _prefix_capable stays True.
        self._paged_prefixed_step = partial(
            prefill_slot_paged_prefixed, attn=impl)
        self._prefix_pages_step = partial(prefill_prefix_pages,
                                          attn=impl)
        # token-level continuous batching (--mixed-batch): ONE jitted
        # step consumes a batch of (row kind, pos, q_len) descriptors —
        # decode rows and prefill-chunk rows in the same launch
        self._mixed_step_fn = partial(mixed_step_paged, attn=impl)
        self._pager = PageAllocator(kv_pages, kv_page_size)
        self._slot_pages = {}
        # slot -> count of SHARED prefix pages in its table row (gauge
        # bookkeeping; the pages themselves ride _slot_pages for the
        # refcounted release)
        self._slot_prefix_pages = {}
        self._prefix_pages_shared = 0
        self._prefix_last_hit = {}
        pool_dtype = self._base_cache_dtype
        if self._kv_dtype_name is not None and not self.kv_quant:
            from cake_tpu.utils.devices import resolve_kv_dtype
            pool_dtype = resolve_kv_dtype(self._kv_dtype_name)
        if self.kv_quant:
            from cake_tpu.kv import Int4PagedKVCache, QuantizedPagedKVCache
            qcls = (Int4PagedKVCache if self._kv_dtype_name == "int4"
                    else QuantizedPagedKVCache)
            self.cache = qcls.create(
                self.config, self.max_slots, kv_pages, kv_page_size,
                self.max_seq_len)
        else:
            self.cache = PagedKVCache.create(
                self.config, self.max_slots, kv_pages, kv_page_size,
                self.max_seq_len, dtype=pool_dtype)
        self._pool_dtype = pool_dtype
        log.info("paged KV: %d pages x %d tokens, %s attention, "
                 "%s storage (%.2f GiB pool; dense %d-slot "
                 "equivalent would be %.2f GiB)",
                 kv_pages, kv_page_size, impl,
                 (self._kv_dtype_name + "+scales") if self.kv_quant
                 else str(pool_dtype),
                 self.cache.memory_bytes() / 2**30, self.max_slots,
                 self.cache.memory_bytes() / 2**30
                 * self.max_slots * self.max_seq_len
                 / (kv_pages * kv_page_size))
        # --kv-host-pages: host-RAM spill tier behind the page
        # allocator (cake_tpu/kv/host_tier.py) — preemption victims'
        # suffix pages and cold shared-prefix pages spill to pinned
        # host memory and stream back on demand, instead of being
        # discarded and recomputed.
        prev_tier = getattr(self, "_host_tier", None)
        self._host_tier = None
        if kv_host_pages is not None:
            from cake_tpu.kv import HostTier
            from cake_tpu.kv.quantized_pool import page_bytes
            tier = HostTier(
                kv_host_pages,
                page_bytes=page_bytes(
                    self.config, kv_page_size,
                    self._kv_dtype_name if self.kv_quant
                    else pool_dtype),
                # spill/restore publish on the engine's event bus
                # (present on first setup AND on a reconfigure rebuild)
                events=getattr(self, "events", None),
                dtype_name=(self._kv_dtype_name if self.kv_quant
                            else jnp.dtype(pool_dtype).name))
            if (prev_tier is not None
                    and prev_tier.page_bytes == tier.page_bytes):
                # reconfigure rebuild: _prepare_fold already decided
                # which entries the switch invalidates (and dropped or
                # cleared them) — carry the survivors into the fresh
                # tier so spilled streams resume from their pages
                # instead of re-prefilling
                for key in prev_tier.keys():
                    ent = prev_tier.pop(key)
                    if ent is not None:
                        tier.put(key, ent)
            self._host_tier = tier
            log.info("kv host tier: %d pages (%.1f MiB capacity)",
                     kv_host_pages,
                     kv_host_pages * tier.page_bytes / 2**20)
        # paged speculative decoding (cake_tpu/spec): the draft model's
        # KV pages live in a SECOND pool with the target pool's page
        # geometry, addressed by the SAME allocator — one page-id
        # space, so draft pages debit the one budget the admission
        # gate counts. The round fn rides the same static attn impl.
        if self._specp is not None:
            from cake_tpu.spec.round import spec_round_paged
            self.d_cache = PagedKVCache.create(
                self._specp.draft_config, self.max_slots, kv_pages,
                kv_page_size, self.max_seq_len, dtype=pool_dtype)
            self._spec_round_fn = partial(spec_round_paged, attn=impl)
            log.info("paged spec: draft pool %d pages x %d tokens "
                     "(%.2f GiB), gamma=%d",
                     kv_pages, kv_page_size,
                     self.d_cache.memory_bytes() / 2**30,
                     self._specp.live_gamma)

    def _capture_cache_identity(self) -> None:
        """Record the cache's placement/dtype so post-error and
        post-switch rebuilds restore identically-sharded zeros even
        after donation freed the live buffers."""
        if isinstance(self.cache, KVCache):
            self._cache_shardings = KVCache(k=self.cache.k.sharding,
                                            v=self.cache.v.sharding)
            self._cache_dtype = self.cache.k.dtype
        else:
            # custom cache pytree (e.g. the sp engine's SPEngineCache):
            # capture (shape, dtype, sharding) NOW — donation frees the
            # buffers, and a post-error rebuild cannot read them then
            self._cache_shardings = jax.tree.map(
                lambda x: (x.shape, x.dtype, x.sharding), self.cache,
                is_leaf=lambda x: hasattr(x, "sharding"))
            # first LEAF, not first field: a quantized paged cache's
            # first field is a QuantPool pytree, not an array
            self._cache_dtype = jax.tree_util.tree_leaves(
                self.cache)[0].dtype

    def _reconfig_supported(self) -> bool:
        return (not self._custom_steps and not self.ring
                and not self._spec and not self._spec_paged
                and not self._multihost)

    def _reconfig_refusal(self) -> str:
        if self._spec:
            return ("speculative serving has no hot-switch fold (the "
                    "draft cache cannot be rebuilt mid-round)")
        if self._spec_paged:
            return ("paged speculative serving has no hot-switch fold "
                    "(the draft pool shares the page allocator a "
                    "switch would swap wholesale)")
        if self.ring:
            return ("ring (sliding-window) caches own their layout; "
                    "a rebuilt ring cannot replay folded positions")
        if self._multihost:
            return ("multi-host serving replays a fixed op stream; "
                    "followers cannot rebuild mid-stream")
        return ("custom step fns own their cache contract; only the "
                "built-in dense/paged engines can hot-switch")

    def current_config(self):
        """The LIVE effective engine config as an autotune point
        (cake_tpu/autotune.EngineConfig) — what /api/v1/health and
        GET /api/v1/autotune report."""
        from cake_tpu.autotune.space import EngineConfig
        kv_dtype = None
        if self.paged:
            if self.kv_quant:
                kv_dtype = self._kv_dtype_name
            elif self._pool_dtype != self._base_cache_dtype:
                # report the storage name only when it actually
                # differs from what an UNSET --kv-dtype resolves to —
                # a policy config omitting kv_dtype must compare equal
                # to an engine whose explicit name resolved to the
                # default (config_key spell-normalization)
                kv_dtype = self._kv_dtype_name
        return EngineConfig(
            slots=self.max_slots,
            decode_scan=self._decode_scan,
            kv_pages=self.cache.n_pages if self.paged else None,
            # cakelint: skip[affinity] taking _switch_lock here would invert the declared order: checkpoint.snapshot calls this under _ckpt_lock (shutdown_save/_snapshot_before_fail); the unlocked read tolerates a torn value mid-switch (informational health/snapshot metadata only)
            kv_page_size=(self._pager.page_size if self.paged else 128),
            kv_dtype=kv_dtype,
            mixed_batch="on" if self._mixed else "off",
            paged_attn=self.paged_attn or "auto",
        )

    def reconfigure(self, config, reason: str = "manual") -> bool:
        """Hot-switch the engine to a new EngineConfig under live load:
        fold every in-flight request's generated tokens into its prompt
        (exactly the PR 8 recovery resubmit minus backoff and crash
        implication), tear down and rebuild the jitted step fns + KV
        pool under the new knobs, and requeue with seniority, class and
        preempt budget preserved. Greedy streams complete
        token-identical at f32 KV across the switch (dense AND paged,
        shared-prefix slots included — tests/test_autotune_engine.py).

        Thread-safe: routed onto the engine thread between iterations
        when the engine is live; a concurrent switch raises
        SwitchInFlightError (the API's 409). Returns True when a
        switch happened, False for a no-op (already at `config`)."""
        from cake_tpu.autotune.space import EngineConfig
        from cake_tpu.serve.errors import SwitchInFlightError
        cfg = (config if isinstance(config, EngineConfig)
               else EngineConfig.from_dict(dict(config)))
        if (self._thread is not None and self._thread.is_alive()
                and threading.current_thread() is not self._thread):
            with self._switch_lock:
                if self._switch_inflight:
                    raise SwitchInFlightError(
                        "a config switch is already in flight")
                self._switch_inflight = True
            try:
                return self._run_on_engine_thread(
                    lambda: self._reconfigure_sync(cfg, reason))
            finally:
                with self._switch_lock:
                    self._switch_inflight = False
        # cakelint: skip[affinity] engine thread not running, or this IS the engine thread (autotune tick); runtime assert distinguishes
        return self._reconfigure_sync(cfg, reason)

    @engine_thread_only
    def _reconfigure_sync(self, new, reason: str) -> bool:
        """Engine-thread body of reconfigure() — between iterations
        only (no device work in flight, exactly the preemption
        invariant)."""
        from cake_tpu.autotune import (
            SWITCH_SECONDS, SWITCHES, set_config_info,
        )
        from cake_tpu.autotune.space import (
            config_key, switch_guard, validate_config,
        )
        # default-aware keys: a policy spelling the engine's default
        # pool dtype explicitly must be a no-op, not a pointless fold
        base = np.dtype(self._base_cache_dtype).name
        cur = self.current_config()
        if (config_key(new, default_kv_dtype=base)
                == config_key(cur, default_kv_dtype=base)):
            return False
        if not self._reconfig_supported():
            raise ValueError("live reconfiguration is unavailable: "
                             + self._reconfig_refusal())
        guard = switch_guard(cur, new)
        if guard is not None:
            raise ValueError(guard)
        validate_config(new, max_seq_len=self.max_seq_len)
        if (self.prefill_chunk is not None
                and self.max_seq_len % self.prefill_chunk != 0):
            raise ValueError("prefill_chunk no longer divides "
                             "max_seq_len")  # unreachable; belt+braces
        t0 = time.perf_counter()
        # the whole mutation runs under _switch_lock: handler-thread
        # submit() takes the same lock around its registration, so an
        # admission lands fully before this switch (fit-checked below
        # and carried) or fully after it (validated by submit's own
        # fail-fast against the NEW pool) — never half-registered
        # across the scheduler/pool swap
        with self._switch_lock:
            # ZERO dropped streams is the contract: refuse a pool no
            # in-flight request fits instead of quietly failing it
            # (the same bound submit() enforces at admission)
            if new.kv_pages is not None:
                per = new.kv_page_size
                for req in list(self._requests.values()):
                    if req.done.is_set():
                        continue
                    need = -(-(len(req.prompt_ids)
                               + req.max_new_tokens) // per)
                    if need > new.kv_pages:
                        raise ValueError(
                            f"refusing switch: rid={req.rid} needs "
                            f"{need} kv pages, the proposed pool has "
                            f"{new.kv_pages} (no stream may be "
                            "dropped)")
            folded = self._prepare_fold(new)
            applied, apply_err = new, None
            try:
                self._apply_exec_config(new)
            except Exception as e:  # noqa: BLE001 — e.g. the new pool
                # OOMs after the old one was freed: restore the OLD
                # config's geometry (zeros pool — the folded streams
                # re-prefill from token ids either way) instead of
                # leaving the engine cacheless and unservable
                log.exception("reconfigure rebuild failed; restoring "
                              "the previous config")
                applied, apply_err = cur, e
                self._apply_exec_config(cur)
            carried = self._requeue_folded(applied, folded)
        if apply_err is not None:
            self._wake.set()
            raise ValueError(
                f"switch to {new.to_dict()} failed; previous config "
                f"restored with {carried} stream(s) requeued: "
                f"{apply_err}") from apply_err
        self.config_epoch += 1
        self.stats.config_switches += 1
        dt = time.perf_counter() - t0
        SWITCHES.labels(reason=reason).inc()
        SWITCH_SECONDS.observe(dt)
        set_config_info(self.current_config())
        entry = {"t": round(time.time(), 3), "reason": reason,
                 "from": cur.to_dict(), "to": new.to_dict(),
                 "seconds": round(dt, 4), "carried": carried,
                 "epoch": self.config_epoch}
        self._switch_log.append(entry)
        if self.events is not None:
            # engine-level summary event (rid=None) beside the
            # per-request ones _requeue_folded published: one line
            # answers what switched, to what, and how many streams rode
            self.events.publish("reconfigured", reason=reason,
                                epoch=self.config_epoch,
                                carried=carried,
                                seconds=round(dt, 4),
                                to=new.to_dict())
        if self._autotuner is not None and reason == "manual":
            # keep the auto controller's view of "current" in sync with
            # an operator's switch (it would otherwise keep proposing
            # moves relative to the superseded config); manual reasons
            # never arm the rollback guard — the operator's call stands
            self._autotuner.on_switched(
                new, cur, self._autotuner.window_service_tps(), reason)
        log.warning("engine reconfigured (%s) in %.3fs: %s -> %s, "
                    "%d stream(s) carried (epoch %d)", reason, dt,
                    cur.to_dict(), new.to_dict(), carried,
                    self.config_epoch)
        self._wake.set()
        return True

    def _storage_name(self) -> str:
        """The LIVE pool's storage-dtype name ("int8"/"int4" for the
        quantized tiers, the numpy dtype name otherwise) — the identity
        a host-tier entry's raw slices are layout-bound to."""
        if self.kv_quant:
            return self._kv_dtype_name
        return np.dtype(self._pool_dtype).name

    def _target_storage_name(self, new) -> str:
        """What _setup_paged_exec would resolve `new`'s storage to —
        mirrors its pool_dtype resolution so the host-tier survival
        check compares the names the rebuild will actually use."""
        if new.kv_dtype in ("int8", "int4"):
            return new.kv_dtype
        if new.kv_dtype is not None:
            from cake_tpu.utils.devices import resolve_kv_dtype
            return np.dtype(resolve_kv_dtype(new.kv_dtype)).name
        return np.dtype(self._base_cache_dtype).name

    def _host_tier_survives(self, new) -> bool:
        """Whether spilled host-tier entries stay valid across a switch
        to `new`: the rebuilt pool must still be paged with the SAME
        page geometry and storage dtype — entries are raw pool slices,
        so a matching pool re-installs them verbatim (page COUNT may
        change freely; entries reference contents, not page ids)."""
        return (self.paged and new.kv_pages is not None
                and new.kv_page_size == self._pager.page_size
                and self._target_storage_name(new) == self._storage_name())

    def _prepare_fold(self, new) -> set:
        """Host-side half of the fold: clear every slot's mappings,
        release pages through the OLD allocator (before the rebuild
        replaces it), and drop state the old pool's bytes back
        (spilled pages, the prefix registry). After this, every
        unfinished request is slotless and will re-prefill from token
        ids — so it is safe regardless of whether the rebuild lands
        the NEW config or rolls back to the old geometry. Caller holds
        _switch_lock, engine thread only. Returns the rids that held
        slots — the streams the switch actually folds (queued requests
        just ride along untouched)."""
        folded = set()
        for slot in range(self.max_slots):
            req = self._slot_req[slot]
            self._slot_req[slot] = None
            if req is not None:
                req.slot = -1
                folded.add(req.rid)
            self._release_slot_pages(slot)
        self._mixed_pending.clear()
        self._page_blocked_rid = None
        self._pending_page_preempt = None
        self._page_starved = False
        self._cur_decode = {}
        self._implicated = ()
        if self._host_tier is not None:
            if self._host_tier_survives(new):
                # PR 9 gap closed: victim entries are raw per-page pool
                # slices (dtype-blind install), valid in ANY rebuilt
                # pool with the same page geometry + storage dtype —
                # keep them so spilled/preempted streams resume from
                # their pages instead of re-prefilling. Prefix entries
                # still die with the registry below (their pids and
                # refcounts do not survive the fold), and a surviving
                # victim whose admission shape no longer matches is
                # dropped by _alloc_slot_pages' entry validation.
                for key in self._host_tier.keys():
                    if not (isinstance(key, tuple) and key
                            and key[0] == "victim"):
                        self._host_tier.drop(key)
            else:
                # geometry or storage dtype changed: spilled pages are
                # OLD-pool layout/dtype; a restore into the rebuilt
                # pool would scatter stale bytes
                self._host_tier.clear()
        if self.paged or new.kv_pages is not None:
            # the paged registry points at pool pages that die with the
            # old pool (and a dense registry's (k, v) entries mean
            # nothing to a paged successor) — auto-prefix heads
            # re-register on their next request
            with self._rid_lock:
                self._prefixes.clear()
                self._auto_pids.clear()
            self._prefix_last_hit = {}
            self._prefix_pages_shared = 0
            _PREFIX_PAGES_SHARED.set(0)
        return folded

    def _requeue_folded(self, applied, folded: set) -> int:
        """Scheduler half of the fold, AFTER the rebuild landed: fold
        every unfinished request into its prompt and requeue under the
        config that was actually applied (the target, or the restored
        old geometry if the rebuild failed) — the recovery resubmit
        minus backoff/implication: seniority and class survive (SLO
        requeue), preempt budgets are untouched, nothing is
        quarantined. Caller holds _switch_lock (handler-thread
        submit() serializes against the scheduler swap on the same
        lock). Returns the number of streams the switch actually
        FOLDED (requests that held a slot — `folded` from
        _prepare_fold; queued requests requeue/resubmit too but are
        not counted or trace-stamped: the switch never touched them)."""
        carried = 0
        if self._slo:
            for rid, req in sorted(self._requests.items()):
                if req.done.is_set():
                    continue
                req._kv_restored = False
                remaining = req.max_new_tokens - len(req.out_tokens)
                if remaining <= 0:
                    # was retiring this iteration — it already holds
                    # every token it asked for
                    self._finish_recovered(req)
                    continue
                # requeue preserves the original enqueue time
                # (seniority) and the preemption count; False just
                # means the request was still QUEUED — nothing to do
                active = self.scheduler.requeue(
                    rid, len(req.prompt_ids) + len(req.out_tokens),
                    remaining)
                if active or rid in folded:
                    self.tracer.span(rid, "reconfigured",
                                     generated=len(req.out_tokens))
                    if self.events is not None:
                        self.events.publish(
                            "reconfigured", rid=rid,
                            generated=len(req.out_tokens))
                    carried += 1
            self.scheduler.resize(applied.slots)
        else:
            # FIFO has no requeue: rebuild the scheduler at the new
            # slot count and resubmit in rid order (arrival order).
            # Capacity must cover QUEUED + formerly-ACTIVE requests:
            # active slots did not count against the old queue cap, so
            # a full queue plus occupied slots would overflow a
            # same-capacity rebuild and drop the overflow — widen to
            # whatever is unfinished right now (at most old_slots over
            # the configured cap; later rebuilds use _max_queue again)
            unfinished = sum(1 for r in self._requests.values()
                             if not r.done.is_set())
            self.scheduler = make_scheduler(
                applied.slots, max(self._max_queue, unfinished),
                priority_classes=False, config=self._sched_cfg)
            for rid, req in sorted(self._requests.items()):
                if req.done.is_set():
                    continue
                req._kv_restored = False
                remaining = req.max_new_tokens - len(req.out_tokens)
                if remaining <= 0:
                    self._finish_recovered(req)
                    continue
                if not self.scheduler.submit(
                        rid, len(req.prompt_ids) + len(req.out_tokens),
                        remaining):
                    # capacity was sized above: cannot happen — but a
                    # dropped stream must be LOUD
                    from cake_tpu.serve.errors import as_engine_error
                    self._drop_request(req, as_engine_error(
                        RuntimeError("reconfigure resubmit failed")))
                    continue
                if rid in folded:
                    self.tracer.span(rid, "reconfigured",
                                     generated=len(req.out_tokens))
                    if self.events is not None:
                        self.events.publish(
                            "reconfigured", rid=rid,
                            generated=len(req.out_tokens))
                    carried += 1
        return carried

    def _apply_exec_config(self, new) -> None:
        """Rebuild the config-dependent execution state under the new
        knobs: step fns, KV cache/pool, per-slot mirrors, PRNG keys and
        the flight recorder's config namespace. Engine thread only,
        after _fold_all_for_switch (no slot holds device state)."""
        from cake_tpu.models.llama.model import prefill_slot_chunk
        B = new.slots
        self.max_slots = B
        self._decode_scan = max(1, new.decode_scan)
        self.paged = new.kv_pages is not None
        self.kv_quant = new.kv_dtype in ("int8", "int4")
        self._kv_dtype_name = new.kv_dtype
        self._mixed = self.paged and (new.mixed_batch or "auto") != "off"
        # free the OLD cache/pool BEFORE building the new one: unlike
        # _reset_after_error (where donation already consumed the
        # buffers), reconfigure's old pool is fully live — keeping
        # both resident would transiently double KV HBM and OOM
        # exactly under the memory pressure a switch is meant to
        # relieve. Safe: every slot was folded (the resume re-prefills
        # from token ids, no old-pool bytes needed); dense prefix
        # entries live outside the cache and are kept/cleared above.
        for leaf in jax.tree_util.tree_leaves(self.cache):
            if hasattr(leaf, "delete"):
                try:
                    leaf.delete()
                except Exception:  # noqa: BLE001 — already-donated
                    pass
        self.cache = None
        if self.paged:
            self._setup_paged_exec(new.kv_pages, new.kv_page_size,
                                   new.paged_attn, self._kv_host_pages)
        else:
            self.paged_attn = None
            self._host_tier = None
            self._prefill_slot = prefill_slot
            self._decode_step = decode_step_ragged
            self._decode_scan_impl = _decode_scan
            self._prefill_chunk_step = prefill_slot_chunk
            self.cache = KVCache.create(self.config, B, self.max_seq_len,
                                        dtype=self._base_cache_dtype)
        self._prefix_capable = True
        self._mixed_chunk = (self.prefill_chunk
                             if self.prefill_chunk is not None
                             else min(256, self.max_seq_len))
        self._capture_cache_identity()
        # per-slot mirrors at the new width
        self._pos = np.zeros(B, np.int64)
        self._last_tok = np.zeros(B, np.int64)
        self._steps = np.zeros(B, np.int64)
        self._temp = np.full(B, self.defaults.temperature or 0.0,
                             np.float32)
        self._top_p = np.ones(B, np.float32)
        self._penalty = np.full(B, self.defaults.repeat_penalty,
                                np.float32)
        self._ring = jnp.full((B, self.defaults.repeat_last_n), -1,
                              jnp.int32)
        self._slot_req = [None] * B
        # fold a reset counter into the rebuild key exactly like
        # _reset_after_error: restoring the startup keys would replay
        # already-consumed sampling streams
        self._reset_count += 1
        self._keys = jax.random.split(
            jax.random.fold_in(jax.random.PRNGKey(self._key_seed),
                               self._reset_count), B)
        self._last_jit = None
        # re-namespace the jit accountant so the new config's compiled
        # signatures can never alias the old config's
        flavor = (f"paged-{self.paged_attn}" if self.paged else "dense")
        self.flight.rebind(
            impl=flavor,
            key_prefix=(self.config, B, self.max_seq_len,
                        str(self._cache_dtype), flavor))

    def autotune_state(self) -> dict:
        """GET /api/v1/autotune: mode, live config, switch/decision
        history, and (auto mode) the controller's window signals."""
        out = {
            "mode": self.autotune_mode,
            "epoch": self.config_epoch,
            "config": self.current_config().to_dict(),
            "switches": self.stats.config_switches,
            "rollbacks": self.stats.config_rollbacks,
            "switch_in_flight": self._switch_inflight,
            "switch_log": list(self._switch_log),
        }
        at = self._autotuner
        if at is not None:
            out["controller"] = at.state()
            out["policy"] = at.policy.to_dict()
        return out

    def _gather_autotune_signals(self, now: float):
        """One sliding-window sample from telemetry the engine already
        keeps: arrival/service deltas from EngineStats, MFU/HBM from
        the flight recorder, queue depth from the scheduler, pool
        occupancy from the allocator, TTFT from the tracer ring."""
        from cake_tpu.autotune import AutotuneSignals
        st = self.stats
        submitted = self._next_rid - 1
        cur = (now, submitted, st.requests_completed,
               st.tokens_generated, st.shed)
        prev, self._autotune_prev = self._autotune_prev, cur
        if prev is None:
            prev = cur
        dt = max(1e-6, now - prev[0])
        util = self.flight.utilization(include_prefill=True)
        pages_frac = 0.0
        if self.paged:
            total = self.cache.n_pages
            pages_frac = (total - self._pager.free_pages) / total
        depths = getattr(self.scheduler, "class_depths", None)
        ttfts = self.tracer.recent_ttfts(32)
        p99 = None
        if ttfts:
            xs = sorted(ttfts)
            p99 = xs[min(len(xs) - 1, int(0.99 * len(xs)))]
        pressure = getattr(self.scheduler, "queue_pressure", None)
        return AutotuneSignals(
            t=now,
            offered_rps=(submitted - prev[1]) / dt,
            completed_rps=(st.requests_completed - prev[2]) / dt,
            service_tps=(st.tokens_generated - prev[3]) / dt,
            queue_depth=self.scheduler.queue_depth,
            queue_depth_by_class=depths() if depths else {},
            mfu=util["mfu"], hbm_util=util["hbm_util"],
            pages_in_use_frac=pages_frac,
            shed_rps=(st.shed - prev[4]) / dt,
            ttft_p99_s=p99,
            # quality signals (obs/slo.py + sched aging pressure): what
            # the policy's v2 guards and the rollback guard key on —
            # the 1m window matches the controller's decision horizon
            ttft_p99_by_class=self.slo.ttft_p99_by_class("1m"),
            attainment=self.slo.attainment_by_class("1m"),
            queue_pressure=pressure() if pressure is not None else 0.0,
        )

    @engine_thread_only
    def _autotune_tick(self) -> None:
        """Auto-mode controller drive, called from the engine loop
        between iterations: sample signals every interval, apply the
        controller's switch/rollback decision inline (this IS the
        engine thread, so the switch happens at a step boundary)."""
        from cake_tpu.autotune import ROLLBACKS
        at = self._autotuner
        if at is None:
            return
        now = time.monotonic()
        if now - self._autotune_last < at.config.interval_s:
            return
        self._autotune_last = now
        decision = at.decide(self._gather_autotune_signals(now))
        if decision is None:
            return
        target, reason = decision
        old = self.current_config()
        pre_rate = at.window_service_tps()
        try:
            if not self._reconfigure_sync(target, reason):
                # spelled-differently-but-identical target (the
                # engine's default-aware key normalization caught it):
                # adopt the target spelling as "current" so the
                # controller stops re-proposing the no-op every tick
                at.on_switched(target, old, pre_rate, "noop")
                return
        except Exception as e:  # noqa: BLE001
            if reason == "rollback":
                # a REFUSED revert (e.g. a stream admitted under the
                # new pool no longer fits the old one) must NOT pin
                # the known-good pre-switch config: stay put — the
                # regressed config is already pinned, so once load
                # drains the policy re-proposes the good one normally
                log.warning("rollback revert refused; staying on the "
                            "current config: %s", e)
            else:
                # an unswitchable policy target must not spin: pin it
                # so the controller stops proposing it
                log.warning("autotune switch refused (%s); pinning: "
                            "%s", reason, e)
                at.pin(target, why=str(e))
            return
        at.on_switched(target, old, pre_rate, reason)
        if reason == "rollback":
            ROLLBACKS.inc()
            self.stats.config_rollbacks += 1

    def _reset_after_error(self) -> None:
        # the jitted steps donate the cache/keys/ring buffers; after a
        # failed call they may already be deleted — rebuild so the engine
        # survives (transient OOM/XLA error must not brick serving)
        self.cache = self._fresh_cache()
        if self._spec:
            self.d_cache = KVCache.create(
                self.draft_config, self.max_slots,
                self.cache.max_seq_len, dtype=self._cache_dtype)
        self._pos[:] = 0
        self._last_tok[:] = 0
        self._steps[:] = 0
        B = self.max_slots
        self._ring = jnp.full((B, self.defaults.repeat_last_n), -1,
                              jnp.int32)
        # fold a reset counter into the rebuild key: restoring the
        # STARTUP keys would replay already-consumed sampling streams
        # (duplicate "random" completions after a transient error).
        # The counter advances identically on every process (followers
        # replay the reset op), so multi-host keys stay in lockstep.
        self._reset_count += 1
        self._keys = jax.random.split(
            jax.random.fold_in(jax.random.PRNGKey(self._key_seed),
                               self._reset_count), B)

    def _fresh_cache(self) -> KVCache:
        if not isinstance(self.cache, KVCache) and not self.paged:
            # custom cache pytree (sp engine): rebuild zeros from the
            # (shape, dtype, sharding) captured at init — the donated
            # buffers themselves may already be freed. PagedKVCache is
            # also not a KVCache but MUST take its own branch below: a
            # zeros rebuild would map every slot to page 0 (create()
            # fills the table with -1) and leak the allocator's pages.
            # jit-with-out_shardings, NOT device_put: each shard zeros
            # in place (no full-buffer host transient), and it is the
            # only valid construction over a multi-process mesh, where
            # device_put to non-addressable devices raises
            # (create_sp_engine_cache precedent).
            specs = list(self._cache_shardings)
            make = jax.jit(
                lambda: type(self.cache)(*(
                    jnp.zeros(shape, dtype)
                    for (shape, dtype, _s) in specs)),
                out_shardings=type(self.cache)(*(
                    s for (_shape, _dtype, s) in specs)))
            return make()
        if self.paged:
            from cake_tpu.models.llama.paged import (
                PageAllocator, PagedKVCache,
            )
            # the rebuild loses every slot's KV; reset the allocator and
            # table bookkeeping with it. Registered prefixes lived in
            # the (now gone) pool pages, so the registry is cleared too
            # — auto-prefix heads re-register on their next request
            self._pager = PageAllocator(self.cache.n_pages,
                                        self.cache.page_size)
            self._slot_pages = {}
            self._slot_prefix_pages = {}
            self._mixed_pending = {}
            self._prefix_pages_shared = 0
            _PREFIX_PAGES_SHARED.set(0)
            with self._rid_lock:
                self._prefixes.clear()
                self._auto_pids.clear()
            self._prefix_last_hit = {}
            with self._rid_lock:
                # staged shipments referenced the failed requests'
                # admissions; post-reset resubmits prefill locally
                self._adopt_store.clear()
            if self._host_tier is not None:
                # spilled victims/prefixes belonged to the failed
                # requests / cleared registry — stale shortcuts only
                self._host_tier.clear()
            if self._specp is not None:
                # every stream's draft/suffix pages lived in the
                # allocator just reset; drop the spec states and
                # rebuild the draft pool (streams re-activate lazily
                # after their recovery resubmit)
                self._specp.spec_streams.clear()
                self.d_cache = PagedKVCache.create(
                    self._specp.draft_config, self.max_slots,
                    self.cache.n_pages, self.cache.page_size,
                    self.max_seq_len, dtype=self._pool_dtype)
            if self.kv_quant:
                from cake_tpu.kv import (Int4PagedKVCache,
                                         QuantizedPagedKVCache)
                qcls = (Int4PagedKVCache
                        if self._kv_dtype_name == "int4"
                        else QuantizedPagedKVCache)
                return qcls.create(
                    self.config, self.max_slots, self.cache.n_pages,
                    self.cache.page_size, self.max_seq_len)
            return PagedKVCache.create(
                self.config, self.max_slots, self.cache.n_pages,
                self.cache.page_size, self.max_seq_len,
                dtype=self._pool_dtype)
        fresh = KVCache.create(self.config, self.max_slots,
                               self.cache.max_seq_len
                               if self.ring else self.max_seq_len,
                               dtype=self._cache_dtype)
        return KVCache(
            k=jax.device_put(fresh.k, self._cache_shardings.k),
            v=jax.device_put(fresh.v, self._cache_shardings.v),
        )

    def _obs_paged_step(self, path: str, seconds: float) -> None:
        """Observe one paged-engine step's wall latency (scan/burst
        callers pass their per-step average). No-op for dense engines —
        the histogram exists to compare the fold vs pallas paged
        attention impls."""
        if self.paged:
            _PAGED_ATTN_STEP.labels(path=path).observe(seconds)

    # -- step telemetry seams (obs/steps.py) -----------------------------

    def _obs_jit(self, name: str, key: tuple, fn, args: tuple,
                 kwargs: Optional[dict] = None):
        """Pre-dispatch compile/cost accounting for one step-fn call:
        a new (engine-config, name, key) signature bumps
        cake_jit_compiles_total{fn} and captures cost_analysis FLOPs /
        bytes from one extra lowering (trace only, no XLA compile) —
        run NOW, before the dispatch consumes its donated buffers.
        Callers time the dispatch and hand the wall to js.finish()."""
        return self.flight.jit_step(
            name, key, lambda: obs_steps.lower_cost(fn, args, kwargs))

    def _page_kw(self) -> dict:
        if not self.paged:
            return {}
        return {"pages_free": self._pager.free_pages,
                "pages_total": self.cache.n_pages}

    def _record_step(self, kind: str, *, rows: int, tokens: int,
                     dispatch_s=None, device_s=None, wall_s=None,
                     js=None, **split) -> None:
        """Append one flight record for the step that just completed,
        attaching the pending dispatch's cost info (js, or the
        engine-thread mailbox _last_jit) and page-pool occupancy.
        `split` carries the mixed step's occupancy breakdown
        (rows_decode / rows_prefill / rows_idle) and the dispatched
        rows' `rids` (the per-request explain's step linkage)."""
        if js is None:
            js, self._last_jit = self._last_jit, None
        self.flight.record(
            kind, rows=rows, tokens=tokens, dispatch_s=dispatch_s,
            device_s=device_s, wall_s=wall_s,
            cost=js.cost if js is not None else None,
            compiled=bool(js is not None and js.new),
            **split, **self._page_kw())

    # -- SLO scheduling: preemption + shed seams (cake_tpu/sched) --------

    def _set_queue_gauges(self) -> None:
        depths = getattr(self.scheduler, "class_depths", None)
        if depths is None:
            return
        for c, d in depths().items():
            _QUEUE_DEPTH.labels(c).set(d)

    @engine_thread_only
    def _maybe_preempt(self) -> None:
        """Reclaim at most one decoding slot per iteration for a
        starved higher class: first for a page-starved admission noted
        last iteration (reason=pages), else for the best-scored waiting
        request when every slot is taken (reason=slots). Victim choice
        (youngest slot of the worst class, preemption budget respected)
        lives in the scheduler; the recompute fold lives here."""
        pend, self._pending_page_preempt = self._pending_page_preempt, None
        cands = []
        if pend is not None:
            cands = [(v, "pages")
                     for v in self.scheduler.preemption_victims(pend)]
        if not cands:
            cands = [(v, "slots")
                     for v in self.scheduler.slot_preemption_victims()]
        for (rid, slot), reason in cands:
            if self._preempt_slot(rid, slot, reason):
                return

    def _preempt_slot(self, rid: int, slot: int, reason: str) -> bool:
        """Recompute-style preemption of one decoding slot: the victim's
        generated tokens fold into its prompt (exactly the
        checkpoint-resume fold, serve/checkpoint.resume — _do_prefill
        re-prefills prompt+generated and the next sampled token is the
        one an uninterrupted greedy run would emit), its pages release
        through the refcounted allocator (shared prefix pages just
        decref), and it requeues WITH its original seniority to
        re-prefill when capacity returns."""
        req = (self._slot_req[slot]
               if 0 <= slot < self.max_slots else None)
        if req is None or req.rid != rid or req.done.is_set():
            return False
        remaining = req.max_new_tokens - len(req.out_tokens)
        if remaining <= 0:
            return False    # retiring this iteration anyway
        if not self.scheduler.requeue(
                rid, len(req.prompt_ids) + len(req.out_tokens),
                remaining, preempted=True):
            return False
        self._slot_req[slot] = None
        req.slot = -1
        req.preemptions += 1
        # spill-over-recompute (cake_tpu/kv host tier): when host pages
        # are free, the victim's OWNED suffix pages (shared prefix
        # pages just decref) move to host RAM before release — resume
        # then restores them and decodes from where it stopped instead
        # of re-prefilling prompt + generated tokens
        spilled = self._spill_victim_pages(req, slot)
        self._release_slot_pages(slot)
        self.stats.preemptions += 1
        _PREEMPTIONS.labels(reason=reason).inc()
        self.tracer.span(rid, "preempted", reason=reason,
                         generated=len(req.out_tokens),
                         spilled=spilled)
        if self.events is not None:
            self.events.publish("preempted", rid=rid, reason=reason,
                                priority=req.priority,
                                generated=len(req.out_tokens),
                                spilled=spilled)
        log.debug("preempted rid=%d (%s, %d tokens %s)", rid, reason,
                  len(req.out_tokens),
                  "spilled to the host tier" if spilled
                  else "fold into the prompt")
        return True

    def _spill_victim_pages(self, req: _Request, slot: int) -> bool:
        """Device->host spill of one preemption victim's owned pages
        (engine thread; the pages are still live — called BEFORE
        _release_slot_pages). False = no tier / no room / mid-prefill
        victim: the recompute fold serves as before."""
        if (self._host_tier is None
                or not getattr(self._sched_cfg, "spill_preempt", True)
                or slot in self._mixed_pending
                or not req.out_tokens):
            return False
        row = self._slot_pages.get(slot) or []
        n_shared = self._slot_prefix_pages.get(slot, 0)
        own = row[n_shared:]
        if not own or not self._host_tier.can_hold(len(own)):
            return False
        from cake_tpu.kv.host_tier import SpilledPages
        try:
            if self._faults is not None:
                # inside the try: an injected fetch fault exercises the
                # documented degradation (fall back to recompute)
                self._faults.check("host_tier.fetch",
                                   step=self.stats.steps)
            arrays = self._host_tier.fetch_pages(self.cache, own)
        except Exception:  # noqa: BLE001 — spill is an optimization
            log.exception("victim spill failed; falling back to "
                          "recompute resume")
            return False
        ok = self._host_tier.put(("victim", req.rid), SpilledPages(
            n_pages=len(own), arrays=arrays, kind="victim",
            pos=int(self._pos[slot]),
            last_tok=int(self._last_tok[slot]),
            n_prefix_tokens=n_shared * self._pager.page_size))
        if ok:
            self.stats.kv_spills += 1
        return ok

    def _release_slot_pages(self, slot: int) -> None:
        """Refcounted release of a slot's page mappings — idempotent
        under the cancel-vs-error race (both teardown paths pop the same
        dict entry; the second caller finds nothing to release). Shared
        prefix pages decref back to the registry's reference instead of
        freeing another slot's live context."""
        if not self.paged or slot < 0:
            return
        # a slot torn down mid-prefill (cancel / preempt / error) must
        # not ride the next mixed step as a ghost chunk row
        self._mixed_pending.pop(slot, None)
        # spec teardown rides the SAME idempotent hook: the stream's
        # draft pages and target suffix-extension pages go back with
        # its base pages, whatever path tears the slot down (finish,
        # cancel, preempt, error) — zero leaked suffix pages
        self._release_spec_state(slot)
        pages = self._slot_pages.pop(slot, None)
        if pages:
            self._pager.release(pages)
        n_shared = self._slot_prefix_pages.pop(slot, 0)
        if n_shared:
            self._prefix_pages_shared -= n_shared
            _PREFIX_PAGES_SHARED.set(self._prefix_pages_shared)

    def _release_spec_state(self, slot: int) -> None:
        """Release a slot's speculative page bookkeeping (idempotent):
        the draft row's pages and the target row's suffix-extension
        pages return to the shared allocator. The device table rows
        keep the stale ids until the next table_set_slot — the same
        already-released-but-still-mapped window every slot teardown
        has, harmless because inactive rows are neither written nor
        read by callers."""
        if self._specp is None:
            return
        st = self._specp.spec_streams.pop(slot, None)
        if st is None:
            return
        if st.d_pages:
            self._pager.release(st.d_pages)
        if st.t_suffix_pages:
            self._pager.release(st.t_suffix_pages)

    def _alloc_slot_pages(self, req: _Request, slot: int,
                          hit=None) -> bool:
        """Admission by pages: map the slot's table row when the pool
        can cover prompt + budget; otherwise requeue the request (it is
        planned again as retiring requests free pages).

        hit: a validated prefix match ((pid, (p_ids, pages, _)), from
        _match_and_validate_prefix) — the slot then allocates only
        SUFFIX + budget pages and maps the shared prefix pages
        (refcount-retained) at the head of its row, so a 1k-token
        system prompt stops costing ceil(1k/page) pages per slot.

        FIFO fairness: a page-starved request becomes the BLOCKING head
        — younger requests requeue behind it instead of being admitted
        past it, or a steady stream of small requests could starve a
        large one forever (the requeue path re-enters the scheduler's
        FIFO at the tail, preserving relative order across cycles)."""
        from cake_tpu.models.llama.paged import table_set_slot
        if self._faults is not None:
            # chaos site for the admission allocator (an injected OOM
            # here surfaces exactly like a real allocation failure)
            self._faults.check("pager.alloc", step=self.stats.steps)
        blocked = getattr(self, "_page_blocked_rid", None)
        if blocked is not None and blocked not in self._requests:
            blocked = self._page_blocked_rid = None  # cancelled/failed
        if blocked is not None and req.rid != blocked:
            # SLO scheduling: a request that OUTRANKS the blocked head
            # (strictly better effective score) may try the pool past
            # it — once the head ages enough its score is best, nothing
            # outranks it, and it keeps first claim on freed pages
            # (the aged blocking head cannot be starved)
            leapfrog = (self._slo
                        and hasattr(self.scheduler, "outranks")
                        and self.scheduler.outranks(req.rid, blocked))
            if not leapfrog:
                return self._requeue_for_pages(req, slot, starved=False)
        prefix_pages: List[int] = []
        n_prefix = 0
        hit_pid = None
        if hit is not None:
            hit_pid = hit[0]
            p_ids, prefix_pages, _ = hit[1]
            n_prefix = len(p_ids)
            if prefix_pages is None:
                # the matched prefix was spilled to the host tier
                # under page pressure: stream it back before mapping
                # (engine thread — pool + table are single-writer)
                prefix_pages = self._restore_prefix(hit_pid)
                if prefix_pages is None:
                    # gone from host too, or no pool room for it right
                    # now: serve this admission without the prefix
                    hit = None
                    hit_pid = None
                    n_prefix = 0
                    prefix_pages = []
        # callers must prefill against the hit that was actually
        # mapped — a restore failure above downgrades it to None, and
        # dispatching the prefix-path prefill anyway would attend
        # never-written pages
        req._effective_hit = hit
        need = len(req.prompt_ids) - n_prefix + req.max_new_tokens
        if self._specp is not None:
            # spec admission gate: admit only when the pool can ALSO
            # cover the stream's worst-case speculative pages — the
            # draft row's whole-context pages (the draft pool shares
            # no prefixes) plus the target row's gamma-token suffix
            # overhang past the base allocation. Activation and
            # per-round extension stay best-effort (a shortfall there
            # degrades the row to plain decode), but admission counting
            # the worst case keeps a pool of spec streams from
            # admitting more residents than it can ever speculate for.
            g = self._specp.live_gamma
            base = len(req.prompt_ids) + req.max_new_tokens
            cap = min(base + g, self.max_seq_len)
            spec_extra = (self._pager.pages_for(cap)
                          + max(self._pager.pages_for(cap)
                                - self._pager.pages_for(base), 0))
            if (self._pager.pages_for(need) + spec_extra
                    > self._pager.free_pages):
                return self._requeue_for_pages(req, slot, starved=True)
        pages = self._pager.alloc(need)
        if pages is None and self._host_tier is not None:
            # consult the host tier before refusing admission: COLD
            # shared-prefix pages (registry-only references, no slot
            # mapping them) spill to host RAM, freeing device pages —
            # the prefix streams back on its next hit instead of being
            # the reason this request waits
            missing = (self._pager.pages_for(need)
                       - self._pager.free_pages)
            if self._spill_cold_prefixes(missing, keep_pid=hit_pid):
                pages = self._pager.alloc(need)
        if pages is None and self._host_tier is not None:
            # still short after the cold spills: oversubscribe — park
            # decode-RESIDENT streams (LRU by admission) in the host
            # tier until the admission fits or no candidate remains
            while (pages is None
                   and self._spill_resident_stream(req.rid)):
                pages = self._pager.alloc(need)
        if pages is None:
            return self._requeue_for_pages(req, slot, starved=True)
        # preempted victim whose pages were spilled (spill-over-
        # recompute): validated against the CURRENT admission shape —
        # a prefix evicted/re-registered between spill and resume
        # changes the row layout, and the stale entry must not restore
        ent = None
        if self._host_tier is not None:
            ent = self._host_tier.peek(("victim", req.rid))
            if ent is not None and (ent.n_prefix_tokens != n_prefix
                                    or ent.n_pages != len(pages)):
                self._host_tier.drop(("victim", req.rid))
                ent = None
            elif ent is not None:
                # counted as a restore; _restore_victim installs it
                ent = self._host_tier.pop(("victim", req.rid))
        if prefix_pages:
            # retain AFTER the suffix alloc: a requeued admission must
            # leave no dangling references behind
            self._pager.retain(prefix_pages)
            self._slot_prefix_pages[slot] = len(prefix_pages)
            self._prefix_pages_shared += len(prefix_pages)
            _PREFIX_PAGES_SHARED.set(self._prefix_pages_shared)
        row = list(prefix_pages) + pages
        self._slot_pages[slot] = row
        self.cache = self.cache._replace(
            table=table_set_slot(self.cache.table, slot, row))
        if self.kv_quant:
            # fresh pages must not inherit a previous occupant's
            # scales (kv/quantized_pool.reset_page_scales); a restore
            # below overwrites them with the spilled scales anyway
            from cake_tpu.kv.quantized_pool import reset_page_scales
            self.cache = reset_page_scales(self.cache, pages)
        if ent is not None:
            self._restore_victim(req, slot, pages, ent)
        if req.rid == blocked:
            self._page_blocked_rid = None
        # LRU stamp for _spill_resident_stream's victim choice: a
        # re-admission (restored or recompute-folded) counts as RECENT
        # use, so the same stream is not immediately re-parked; the
        # token watermark starts its anti-thrash residency quantum
        req._admit_seq = self._admit_seq
        req._resident_base = len(req.out_tokens)
        self._admit_seq += 1
        return True

    def _restore_victim(self, req: _Request, slot: int,
                        pages: List[int], ent) -> None:
        """host->device restore of a spilled preemption victim: the
        saved page contents scatter into the freshly-mapped suffix
        pages (bit-identical round trip) and the slot's mirrors resume
        at the spilled frontier — the next decode step samples exactly
        the token an uninterrupted run would have. Sets _kv_restored
        so the admission path skips the recompute prefill. ent: the
        validated entry _alloc_slot_pages already popped from the
        host tier."""
        from cake_tpu.kv.host_tier import HostTier
        if self._faults is not None:
            # an injected install fault propagates into the iteration
            # failure — the recovery path resubmits the victim through
            # the recompute fold (the entry was already popped)
            self._faults.check("host_tier.install",
                               step=self.stats.steps)
        self.cache = HostTier.install_pages(self.cache, pages,
                                            ent.arrays)
        self._temp[slot] = req.temperature
        self._top_p[slot] = req.top_p
        self._penalty[slot] = req.repeat_penalty
        self._prime_ring(slot, list(req.prime_tokens)
                         + list(req.out_tokens))
        self._pos[slot] = ent.pos
        self._last_tok[slot] = ent.last_tok
        self.stats.kv_restores += 1
        req._kv_restored = True
        self.tracer.span(req.rid, "kv_restored", pages=ent.n_pages)
        log.debug("restored rid=%d from the host tier (%d pages, "
                  "pos %d)", req.rid, ent.n_pages, ent.pos)

    def _capture_shipment(self, req: _Request) -> None:
        """Disaggregated PREFILL host (engine thread, inside _emit's
        retirement, before _release_slot_pages frees the row): fetch
        the pages holding the prompt's KV — raw pool slices, scale
        sidecars included, dtype-blind — and hand a Shipment to the
        request's ship_sink. Failure hands None: the decode peer
        degrades to local prefill, so this must never raise."""
        from cake_tpu.kv.host_tier import HostTier, pool_dtype_name
        from cake_tpu.kv.transfer import Shipment
        ship = None
        try:
            if self._faults is not None:
                # inside the try: an injected ship fault degrades to
                # the peer's local prefill, like a real fetch failure
                self._faults.check("kv.ship", step=self.stats.steps)
            if not self.paged or not req.out_tokens:
                raise ValueError("nothing to ship (unpaged or no "
                                 "first token)")
            row = self._slot_pages.get(req.slot) or []
            P = self._pager.page_size
            n_tokens = len(req.prompt_ids)
            n_written = -(-n_tokens // P)
            if n_written > len(row):
                raise ValueError(
                    f"slot row holds {len(row)} pages; prompt needs "
                    f"{n_written}")
            pages = row[:n_written]
            ship = Shipment(
                epoch=0,   # stamped by the plane with the PEER's epoch
                dtype=pool_dtype_name(self.cache),
                page_size=P, n_tokens=n_tokens, n_written=n_written,
                first_tok=int(req.out_tokens[0]), pages=list(pages),
                arrays=HostTier.fetch_pages(self.cache, pages),
                handoff={
                    # the journal admit/emit schema's fields — what the
                    # decode host needs to adopt the stream
                    "rid": req.rid, "prompt_len": n_tokens,
                    "max_new_tokens": req.max_new_tokens,
                    "temperature": req.temperature,
                    "top_p": req.top_p,
                    "repeat_penalty": req.repeat_penalty,
                    "priority": req.priority,
                    "first_lp": float(req.out_logprobs[0])
                    if req.out_logprobs else 0.0,
                })
            self.stats.kv_ships += 1
            self.tracer.span(req.rid, "kv_shipped", pages=n_written)
        except Exception:  # noqa: BLE001 — shipping is best-effort
            log.exception("kv shipment capture failed rid=%d; peer "
                          "will prefill locally", req.rid)
            ship = None
        try:
            req.ship_sink(ship)
        except Exception:  # noqa: BLE001 — never raise into _emit
            log.exception("ship_sink failed rid=%d", req.rid)

    def _adopt_install(self, req: _Request, slot: int, ent) -> bool:
        """Disaggregated DECODE host (engine thread, from _do_prefill/
        _mixed_admit after the row is allocated): install the shipped
        pages into the slot's freshly-mapped row and resume the stream
        at the shipped frontier — mirrors _restore_victim, with the
        peer-sampled first token emitted verbatim. False = refused
        (stale epoch, geometry drift, injected fault): the caller
        falls through to whole-prompt local prefill, which rewrites
        the row's pages and scales — the documented degradation."""
        from cake_tpu.kv.host_tier import HostTier, pool_dtype_name
        from cake_tpu.kv.transfer import note_adopt
        outcome = "fault"
        try:
            if self._faults is not None:
                self._faults.check("kv.adopt", step=self.stats.steps)
            if ent.epoch != self.config_epoch:
                outcome = "epoch"
                raise ValueError(
                    f"shipment config epoch {ent.epoch} != engine "
                    f"epoch {self.config_epoch} (reconfigured while "
                    "the shipment flew)")
            pool_dt = pool_dtype_name(self.cache)
            row = self._slot_pages.get(slot) or []
            if (ent.page_size != self._pager.page_size
                    or ent.dtype != pool_dt
                    or ent.n_tokens != len(req.prompt_ids)
                    or ent.n_written > len(row)):
                outcome = "geometry"
                raise ValueError(
                    f"shipment geometry (page_size={ent.page_size}, "
                    f"dtype={ent.dtype}, n_tokens={ent.n_tokens}, "
                    f"n_written={ent.n_written}) does not fit this "
                    f"pool (page_size={self._pager.page_size}, "
                    f"dtype={pool_dt}, row={len(row)} pages)")
            self.cache = HostTier.install_pages(
                self.cache, row[:ent.n_written], ent.arrays)
        except Exception:  # noqa: BLE001 — adoption is best-effort
            note_adopt(outcome)
            log.exception("kv adoption refused rid=%d; degrading to "
                          "local prefill", req.rid)
            return False
        self._temp[slot] = req.temperature
        self._top_p[slot] = req.top_p
        self._penalty[slot] = req.repeat_penalty
        self._prime_ring(slot, list(req.prime_tokens)
                         + [ent.first_tok])
        self._pos[slot] = ent.n_tokens
        self._last_tok[slot] = ent.first_tok
        self.stats.kv_adopts += 1
        note_adopt("adopted")
        self.tracer.span(req.rid, "kv_adopted", pages=ent.n_written)
        if self.events is not None:
            self.events.publish("kv_adopted", rid=req.rid,
                                pages=ent.n_written, dtype=ent.dtype)
        # the peer's first token emits verbatim — identity with the
        # colocated engine is by construction, and the stream's SSE
        # starts here, not after a local re-prefill
        self._emit(req, ent.first_tok,
                   logprob=float(ent.handoff.get("first_lp", 0.0)))
        return True

    def _spill_cold_prefixes(self, n_pages_needed: int,
                             keep_pid=None) -> int:
        """Spill least-recently-hit COLD prefixes (every page at
        refcount 1 — only the registry holds them) to the host tier
        until n_pages_needed device pages are freed, skipping keep_pid
        (the admission's own matched prefix). Engine thread only.
        Returns the number of pages freed."""
        if self._host_tier is None or n_pages_needed <= 0:
            return 0
        from cake_tpu.kv.host_tier import SpilledPages
        with self._rid_lock:
            entries = list(self._prefixes.items())
        entries.sort(
            key=lambda kv: self._prefix_last_hit.get(kv[0], 0.0))
        freed = 0
        for pid, (p_ids, pages, _extra) in entries:
            if freed >= n_pages_needed:
                break
            if pid == keep_pid or pages is None:
                continue
            if any(self._pager.refcount(p) != 1 for p in pages):
                continue          # hot: some slot maps these pages
            if not self._host_tier.can_hold(len(pages)):
                continue
            try:
                if self._faults is not None:
                    self._faults.check("host_tier.fetch",
                                       step=self.stats.steps)
                arrays = self._host_tier.fetch_pages(self.cache, pages)
            except Exception:  # noqa: BLE001 — spill is optional
                log.exception("cold prefix spill failed (pid=%d)", pid)
                continue
            if not self._host_tier.put(
                    ("prefix", pid),
                    SpilledPages(n_pages=len(pages), arrays=arrays,
                                 kind="prefix")):
                continue
            with self._rid_lock:
                self._prefixes[pid] = (p_ids, None, ("prefix", pid))
            self._pager.release(pages)
            self.stats.kv_spills += 1
            freed += len(pages)
            log.debug("spilled cold prefix %d (%d pages) to the host "
                      "tier", pid, len(pages))
        return freed

    def _restore_prefix(self, pid: int) -> Optional[List[int]]:
        """host->device restore of a spilled prefix: allocate fresh
        pool pages, scatter the saved contents back, and re-point the
        registry entry. None when the host entry was LRU-evicted (the
        prefix is gone — unregister it so matches stop) or the pool
        has no room right now (entry kept; the hit degrades to a
        whole-prompt prefill for this admission)."""
        if self._host_tier is None:
            return None
        from cake_tpu.kv.host_tier import HostTier
        ent = self._host_tier.peek(("prefix", pid))
        with self._rid_lock:
            entry = self._prefixes.get(pid)
        if entry is None:
            if ent is not None:
                self._host_tier.drop(("prefix", pid))
            return None
        if ent is None:
            # evicted from the host tier: the prefix exists nowhere —
            # drop the registration (auto-prefix re-registers its head
            # on the next matching request, the stale-pid heal path)
            with self._rid_lock:
                self._prefixes.pop(pid, None)
            return None
        pages = self._pager.alloc(ent.n_pages * self._pager.page_size)
        if pages is None:
            return None
        if self._faults is not None:
            self._faults.check("host_tier.install",
                               step=self.stats.steps)
        ent = self._host_tier.pop(("prefix", pid))
        self.cache = HostTier.install_pages(self.cache, pages,
                                            ent.arrays)
        with self._rid_lock:
            self._prefixes[pid] = (entry[0], pages, None)
        self._prefix_last_hit[pid] = time.monotonic()
        self.stats.kv_restores += 1
        log.debug("restored prefix %d from the host tier (%d pages)",
                  pid, ent.n_pages)
        return pages

    def _requeue_for_pages(self, req: _Request, slot: int,
                           starved: bool) -> bool:
        self._slot_req[slot] = None
        req.slot = -1
        self._page_starved = True
        if starved and getattr(self, "_page_blocked_rid", None) is None:
            self._page_blocked_rid = req.rid
        if self._slo:
            # requeue (not cancel+submit): seniority survives, so the
            # aging score keeps counting from the original admission
            ok = self.scheduler.requeue(
                req.rid, len(req.prompt_ids) + len(req.out_tokens),
                req.max_new_tokens - len(req.out_tokens))
        else:
            # folded shape, like the requeue above: a parked
            # decode-resident stream (_spill_resident_stream) can be
            # page-starved at RE-admission — resubmitting its original
            # budget would let the scheduler grant max_new_tokens on
            # top of what it already generated
            self.scheduler.cancel(req.rid)
            ok = self.scheduler.submit(
                req.rid, len(req.prompt_ids) + len(req.out_tokens),
                req.max_new_tokens - len(req.out_tokens))
        if not ok:
            req.error = RuntimeError(
                "kv page pool exhausted and admission queue full")
            self._requests.pop(req.rid, None)
            if getattr(self, "_page_blocked_rid", None) == req.rid:
                self._page_blocked_rid = None
            if self._host_tier is not None:
                self._host_tier.drop(("victim", req.rid))
            self._journal_retire(req, "error", error=str(req.error))
            self.tracer.finish(req.rid, "error", error=str(req.error))
            req.done.set()
        else:
            self.tracer.span(req.rid, "requeued")
            if starved and self._slo and self._preemption:
                # note the starved class for the TOP of the next
                # iteration: preempting mid-wave would leave the
                # already-planned decode rows writing through a
                # released page-table row
                r = CLASS_RANK[req.priority]
                cur = self._pending_page_preempt
                self._pending_page_preempt = (r if cur is None
                                              else min(cur, r))
        return False

    def _live_decode_rows(self, decode_plan):
        """Re-validate a decode plan after a mid-wave resident spill:
        plan() ran before admissions, so a slot parked by
        _spill_resident_stream may still carry a planned decode row —
        pointing at pages already released (and possibly re-allocated
        to the admission that triggered the park)."""
        self._resident_parked = False
        live = []
        for rid, slot in decode_plan:
            req = self._slot_req[slot]
            if req is not None and req.rid == rid:
                live.append((rid, slot))
        return live

    @engine_thread_only
    def _spill_resident_stream(self, exclude_rid: int) -> bool:
        """Decode-resident spill — oversubscribe the KV pool like
        virtual memory: when admission would be refused even after
        cold-prefix spills, park the LEAST-RECENTLY-ADMITTED decoding
        stream's owned suffix pages in the host tier and requeue it.
        The victim resumes through the same two paths a preemption
        victim does (_restore_victim when its pages round-trip, the
        fold-tokens-into-prompt recompute otherwise), so its token
        stream is identical to an uninterrupted run. Returns True when
        a stream was parked (its pages are now free), False when no
        candidate qualifies — callers retry the allocation per park.

        Candidates come from _cur_decode (this iteration's planned
        decode rows), NEVER same-wave admissions: a re-admitted
        preemption victim earlier in this prefill wave has out_tokens
        but its prefill may still be in flight on device."""
        if (self._host_tier is None
                or not getattr(self._sched_cfg, "spill_resident", True)):
            return False
        quantum = getattr(self._sched_cfg, "resident_quantum", 8)
        best = None
        for slot, rid in self._cur_decode.items():
            req = (self._slot_req[slot]
                   if 0 <= slot < self.max_slots else None)
            if (req is None or req.rid != rid or req.rid == exclude_rid
                    or req.done.is_set() or slot in self._mixed_pending
                    or not req.out_tokens
                    or req.max_new_tokens - len(req.out_tokens) <= 0):
                continue
            # anti-thrash: the victim must have USED its residency —
            # quantum-sized time-slices, not one-token ping-pong
            if (len(req.out_tokens)
                    - getattr(req, "_resident_base", 0) < quantum):
                continue
            own = (self._slot_pages.get(slot)
                   or [])[self._slot_prefix_pages.get(slot, 0):]
            # FREE capacity only — a park must never LRU-evict an
            # existing entry: a spilled prefix is its only copy (an
            # eviction unregisters it), and evicting another parked
            # stream just trades one recompute for another
            if not own or len(own) > self._host_tier.free_pages:
                continue
            seq = getattr(req, "_admit_seq", 0)
            if best is None or seq < best[0]:
                best = (seq, rid, slot)
        if best is None:
            return False
        _, rid, slot = best
        req = self._slot_req[slot]
        remaining = req.max_new_tokens - len(req.out_tokens)
        if self._slo:
            # seniority survives (the _preempt_slot discipline): the
            # parked stream keeps aging from its original admission
            if not self.scheduler.requeue(
                    rid, len(req.prompt_ids) + len(req.out_tokens),
                    remaining):
                return False
        else:
            # resubmit as it will RE-prefill: generated tokens folded
            # into the prompt, budget reduced to the remainder — the
            # scheduler retires on ITS budget count, so the original
            # max_new here would let the stream over-generate
            self.scheduler.cancel(rid)
            if not self.scheduler.submit(
                    rid, len(req.prompt_ids) + len(req.out_tokens),
                    remaining):
                # admission queue full: the victim has nowhere to wait
                # — it errors exactly like a page-starved admission
                # with a full queue (_requeue_for_pages), and its
                # pages still come back to the pool
                self._slot_req[slot] = None
                req.slot = -1
                self._release_slot_pages(slot)
                self._resident_parked = True
                req.error = RuntimeError(
                    "kv page pool exhausted and admission queue full")
                self._requests.pop(rid, None)
                self._journal_retire(req, "error", error=str(req.error))
                self.tracer.finish(rid, "error", error=str(req.error))
                req.done.set()
                return True
        from cake_tpu.kv.host_tier import note_resident_spill
        self._slot_req[slot] = None
        req.slot = -1
        spilled = self._spill_victim_pages(req, slot)
        self._release_slot_pages(slot)
        self._resident_parked = True
        self.stats.kv_resident_spills += 1
        note_resident_spill()
        self.tracer.span(rid, "resident_spilled",
                         generated=len(req.out_tokens), spilled=spilled)
        if self.events is not None:
            self.events.publish("resident_spilled", rid=rid,
                                generated=len(req.out_tokens),
                                spilled=spilled)
        log.debug("parked decode-resident rid=%d (%d tokens %s)", rid,
                  len(req.out_tokens),
                  "spilled to the host tier" if spilled
                  else "fold into the prompt")
        return True

    def _do_prefill(self, rid: int, slot: int, defer: bool = False):
        """Prefill one admission. defer=False: dispatch, fetch, emit —
        the multi-host lockstep path. defer=True: dispatch only; returns
        (req, t0, slot, dev) for _do_prefill_batch, which fetches every
        admission's first token in ONE host round-trip (a per-admission
        fetch costs ~100ms over a remote-dispatch tunnel — the dominant
        term in TTFT when a wave of requests arrives together)."""
        req = self._requests.get(rid)
        if req is None:  # cancelled between plan and here
            self.scheduler.cancel(rid)
            return None
        self.tracer.prefill_start(rid)
        t0 = time.perf_counter()
        req.slot = slot
        self._slot_req[slot] = req
        ids = req.prompt_ids
        prime = req.prime_tokens
        if req.out_tokens:
            # preempted-and-requeued request (tokens exist before this
            # prefill only via preemption): recompute-style resume —
            # the generated tokens fold into the prompt and the
            # penalty ring reconstructs over the whole transcript,
            # exactly the checkpoint-resume fold (serve/checkpoint
            # .resume), so the re-prefill leaves cache and sampling
            # state as an uninterrupted run would have them
            ids = list(req.prompt_ids) + list(req.out_tokens)
            prime = list(req.prime_tokens) + list(req.out_tokens)
        # this admission is the failure blast radius from here on; the
        # fault site carries the prefill length so match_len= rules can
        # target one request's prefill (the poison-request drill)
        self._implicated = ((rid, slot),)
        if self._faults is not None:
            self._faults.check("engine.prefill", step=self.stats.steps,
                               n_tokens=len(ids))
        # shipped-prefill adoption (disaggregated decode host): a
        # staged shipment replaces BOTH the prefix match and the local
        # compute — the peer's pages hold the whole prompt, so the row
        # allocates unshared. PEEK only here: the entry must survive a
        # pool-exhausted requeue; it pops after the row exists.
        with self._rid_lock:
            adopt = self._adopt_store.get(rid)
        # match BEFORE page admission: a paged prefix hit changes the
        # allocation itself (suffix + budget pages only, prefix pages
        # mapped shared)
        hit = (self._match_and_validate_prefix(ids)
               if self._prefix_capable and adopt is None else None)
        if self.paged and not self._alloc_slot_pages(req, slot, hit):
            return None   # pool exhausted: requeued (or failed) inside
        if self.paged:
            hit = req._effective_hit   # spilled-prefix restore failure
        if getattr(req, "_kv_restored", False):
            # spilled preemption victim restored from the host tier:
            # KV and sampling state already sit at the preemption
            # frontier — no prefill dispatch at all (the token that
            # recompute-resume would re-derive was already emitted)
            req._kv_restored = False
            return None
        if adopt is not None:
            with self._rid_lock:
                self._adopt_store.pop(rid, None)
            if not req.out_tokens \
                    and self._adopt_install(req, slot, adopt):
                return None   # pages installed, first token emitted
            # refused (stale epoch / geometry / injected fault): fall
            # through — whole-prompt prefill rewrites the row's pages
            # and scales, the documented degradation
        n_top = self._n_top_for([slot])
        if hit is not None:
            hit_pid, entry = hit
            # the follower resolves the pid in ITS registry (mirrored by
            # register_prefix ops — wire ordering guarantees presence)
            # and re-derives the window plan from shared config —
            # identical dispatch on every process
            self._publish({
                "op": "prefill_prefixed", "pid": hit_pid, "ids": ids,
                "slot": slot, "temp": req.temperature,
                "top_p": req.top_p, "penalty": req.repeat_penalty,
                "prime": list(prime), "n_top": n_top,
            })
            out = self._prefixed_prefill_device(
                hit_pid, ids, slot, req.temperature, req.top_p,
                req.repeat_penalty, prime, n_top=n_top,
                entry=entry, defer=defer)
            self.stats.prefix_hits += 1
            if self.events is not None:
                self.events.publish("prefix_hit", rid=rid, pid=hit_pid,
                                    tokens_saved=len(entry[0]))
        else:
            # covers whole-prompt AND chunked prefill — _prefill_device
            # picks between them from (prefill_chunk, len) alone, the
            # same deterministic rule a multi-host follower applies to
            # this published op
            self._publish({
                "op": "prefill", "ids": ids, "slot": slot,
                "temp": req.temperature, "top_p": req.top_p,
                "penalty": req.repeat_penalty,
                "prime": list(prime), "n_top": n_top,
            })
            out = self._prefill_device(
                ids, slot, req.temperature, req.top_p,
                req.repeat_penalty, prime, n_top=n_top,
                defer=defer)
        if defer:
            return (req, t0, slot, out)
        tok, lp, top = out
        dt = time.perf_counter() - t0
        self.stats.prefill_time_s += dt
        self._obs_paged_step("prefill", dt)
        self._record_step("prefill", rows=1, tokens=1, wall_s=dt,
                          rids=(rid,))
        self._emit(req, tok, logprob=lp, top=top)
        return None

    # admissions per first-token fetch in _do_prefill_batch: a fetch
    # costs one host round-trip (~100ms over a remote-dispatch tunnel),
    # a prefill dispatch ~tens of ms — groups of 4 amortize the fetch
    # 4x while early arrivals in a big wave still stream their first
    # token after ~4 prefills instead of after the whole wave (p50 TTFT)
    PREFILL_FLUSH = 4

    @engine_thread_only
    def _do_prefill_batch(self, prefill_plan) -> None:
        """Admit a wave of requests with one first-token fetch per
        PREFILL_FLUSH admissions: each group's prefills + first-token
        samples are dispatched back to back (the device chains them
        through the donated cache), then a single jax.device_get
        collects the group's first tokens. Single-host only — a
        follower replays per-admission ops synchronously."""
        pend = []
        pend_js = []   # each admission's _JitStep, in pend order

        def flush():
            # the whole GROUP is the failure blast radius: a deferred
            # prefill error (dispatched async above) materializes at
            # this device_get, after later admissions overwrote the
            # per-admission _implicated — without this, an organic
            # poison prefill would charge its crash to whichever
            # admission happened to defer last
            self._implicated = tuple(
                (req.rid, slot) for (req, _t0, slot, _dev) in pend)
            hosts = jax.device_get([dev for (_, _, _, dev) in pend])
            # one wall-clock interval per GROUP: the admissions overlap
            # (dispatched back to back, fetched together), so summing
            # per-request spans would count the same wall time up to
            # PREFILL_FLUSH times
            dt = time.perf_counter() - pend[0][1]
            self.stats.prefill_time_s += dt
            self._obs_paged_step("prefill", dt / len(pend))
            # one record per admission GROUP (per-admission walls would
            # multi-count the overlap), with the group's SUMMED FLOPs /
            # bytes over the group wall — and a compile anywhere in the
            # group flags the record (a single admission's js would hide
            # the other members' costs and compiles)
            flops = sum(js.cost.flops for js in pend_js
                        if js is not None and js.cost is not None)
            nbytes = sum(js.cost.bytes_accessed for js in pend_js
                         if js is not None and js.cost is not None)
            cost = (obs_steps.CostInfo(flops=flops, bytes_accessed=nbytes)
                    if flops or nbytes else None)
            self.flight.record(
                "prefill", rows=len(pend), tokens=len(pend), wall_s=dt,
                cost=cost,
                compiled=any(js is not None and js.new for js in pend_js),
                rids=[req.rid for (req, _t0, _s, _d) in pend],
                **self._page_kw())
            for (req, t0, slot, _), host in zip(pend, hosts):
                tok, lp, top = self._finish_prefill_complete(slot, host)
                self._emit(req, tok, logprob=lp, top=top)
            pend.clear()
            pend_js.clear()

        for rid, slot in prefill_plan:
            p = self._do_prefill(rid, slot, defer=True)
            if p is not None:
                pend.append(p)
                pend_js.append(self._last_jit)
                self._last_jit = None
            if len(pend) >= self.PREFILL_FLUSH:
                flush()
        if pend:
            flush()

    # -- token-level continuous batching (--mixed-batch) ------------------

    def _prime_ring(self, slot: int, prime) -> None:
        """Reset one slot's repeat-penalty ring + step counter, seeding
        it from `prime` (checkpoint resume / preemption fold): each
        prior token at its true step index and the counter continuing
        from there, so subsequent writes land where they always would."""
        self._ring = self._ring.at[slot].set(-1)
        self._steps[slot] = 0
        if prime:
            N = self._ring.shape[1]
            row = np.full(N, -1, np.int32)
            start = max(0, len(prime) - N)
            for i, t in enumerate(prime[start:], start=start):
                row[i % N] = t
            self._ring = self._ring.at[slot].set(jnp.asarray(row))
            self._steps[slot] = len(prime)

    @engine_thread_only
    def _do_mixed(self, prefill_plan, decode_plan) -> None:
        """One engine iteration of token-level continuous batching:
        admissions map their pages and join the VERY NEXT device step
        as prefill-chunk rows alongside the decode rows — no
        alternating prefill-then-decode phases, so the MXU sees one
        well-occupied mixed launch instead of two under-occupied ones.

        decode_scan interaction (the K-step-burst admission-delay fix):
        scan bursts only run while NO prompt is mid-prefill and nobody
        waits in the queue (_scan_steps_for's queue gate); the moment a
        request is admitted, the loop falls back to single mixed steps
        so its chunks ride every iteration instead of stalling behind a
        K-token scan burst."""
        for rid, slot in prefill_plan:
            self._mixed_admit(rid, slot)
        if not self._mixed_pending:
            # pure decode: the phase path's programs are strictly
            # cheaper here (C=1 step, K-step scan bursts) and no
            # admission is waiting on a step boundary
            if decode_plan and self._resident_parked:
                # an admission above parked a decode-resident slot
                # (_spill_resident_stream): drop its stale row before
                # the device step (_mixed_dispatch re-validates per
                # row; these phase-path programs do not)
                decode_plan = self._live_decode_rows(decode_plan)
            if decode_plan and self._specp is not None:
                # spec rows ride one batched draft+verify round; rows
                # the partition leaves behind (prefill frontier, page
                # pressure, sampling options, window cap, degraded)
                # fall through to the plain decode paths below
                decode_plan = self._do_spec_paged(decode_plan)
            if decode_plan:
                n = self._scan_steps_for(decode_plan)
                if n > 1:
                    self._decode_burst(decode_plan, n)
                else:
                    self._do_decode(decode_plan)
            return
        self._mixed_dispatch(decode_plan)

    def _mixed_admit(self, rid: int, slot: int) -> None:
        """Admission half of _do_prefill for the mixed path: page
        mapping, prefix matching, and sampling-state setup — but NO
        device dispatch; the prompt's windows ride the next mixed
        step(s) as chunk rows."""
        req = self._requests.get(rid)
        if req is None:  # cancelled between plan and here
            self.scheduler.cancel(rid)
            return
        self.tracer.prefill_start(rid)
        req.slot = slot
        self._slot_req[slot] = req
        ids = req.prompt_ids
        prime = req.prime_tokens
        if req.out_tokens:
            # preempted-and-requeued: recompute-style resume — the
            # generated tokens fold into the prompt and the penalty
            # ring reconstructs over the whole transcript (_do_prefill
            # precedent, serve/checkpoint.resume semantics)
            ids = list(req.prompt_ids) + list(req.out_tokens)
            prime = list(req.prime_tokens) + list(req.out_tokens)
        # blast radius + content-keyed fault site (see _do_prefill)
        self._implicated = ((rid, slot),)
        if self._faults is not None:
            self._faults.check("engine.prefill", step=self.stats.steps,
                               n_tokens=len(ids))
        # shipped-prefill adoption: PEEK before the prefix match (an
        # adopted row allocates unshared), pop after the row exists —
        # see _do_prefill for the full discipline
        with self._rid_lock:
            adopt = self._adopt_store.get(rid)
        hit = (self._match_and_validate_prefix(ids)
               if self._prefix_capable and adopt is None else None)
        if self.paged and not self._alloc_slot_pages(req, slot, hit):
            return   # pool exhausted: requeued (or failed) inside
        hit = req._effective_hit       # spilled-prefix restore failure
        if getattr(req, "_kv_restored", False):
            # spilled victim restored (see _do_prefill): the slot
            # resumes mid-decode — it must NOT ride the next mixed
            # step as a chunk row
            req._kv_restored = False
            return
        if adopt is not None:
            with self._rid_lock:
                self._adopt_store.pop(rid, None)
            if not req.out_tokens \
                    and self._adopt_install(req, slot, adopt):
                # the slot resumes as a DECODE row from the shipped
                # frontier — it must not also ride as a chunk row
                return
            # refused: fall through to local chunked prefill
        off = 0
        if hit is not None:
            # shared prefix pages already mapped at the row head
            # (_alloc_slot_pages): the windows start AFTER them
            off = len(hit[1][0])
            self.stats.prefix_hits += 1
            _PREFIX_PAGED_HITS.inc()
            _PREFIX_TOKENS_SAVED.inc(off)
            if self.events is not None:
                self.events.publish("prefix_hit", rid=req.rid,
                                    pid=hit[0], tokens_saved=off)
        self._temp[slot] = req.temperature
        self._top_p[slot] = req.top_p
        self._penalty[slot] = req.repeat_penalty
        self._prime_ring(slot, prime)
        self._pos[slot] = off
        self._mixed_pending[slot] = {"req": req, "ids": ids, "off": off}

    def _mixed_dispatch(self, decode_plan) -> None:
        """Build and run ONE mixed step: every decode row contributes
        its last token (q_len=1), every mid-prefill slot its next
        window (q_len=n at its current offset); rows whose window ends
        their prompt sample their first token from the same launch the
        decode rows sample their next."""
        t0 = time.perf_counter()
        # blast radius: every decode row AND every mid-prefill slot
        # rides this one launch
        self._implicated = tuple(
            [(rid, slot) for rid, slot in decode_plan]
            + [(p["req"].rid, slot)
               for slot, p in self._mixed_pending.items()])
        if self._faults is not None:
            self._faults.check("engine.mixed", step=self.stats.steps)
        B, C = self.max_slots, self._mixed_chunk
        tokens = np.zeros((B, C), np.int64)
        pos = np.zeros(B, np.int64)
        qlen = np.zeros(B, np.int64)
        active = np.zeros(B, bool)
        decode_rows: List[int] = []
        for rid, slot in decode_plan:
            if slot in self._mixed_pending:
                continue    # still prefilling: rides as a chunk row
            req = self._slot_req[slot]
            if req is None or req.rid != rid:
                continue
            tokens[slot, 0] = self._last_tok[slot]
            pos[slot] = min(self._pos[slot], self.max_seq_len - 1)
            qlen[slot] = 1
            active[slot] = True
            decode_rows.append(slot)
        chunk_rows: List[int] = []
        finished: List[int] = []
        for slot in sorted(self._mixed_pending):
            p = self._mixed_pending[slot]
            ids, off = p["ids"], p["off"]
            n = min(C, len(ids) - off)
            tokens[slot, :n] = ids[off:off + n]
            pos[slot] = off
            qlen[slot] = n
            active[slot] = True
            chunk_rows.append(slot)
            if off + n >= len(ids):
                finished.append(slot)
        if not decode_rows and not chunk_rows:
            return
        fargs = (self.params, jnp.asarray(tokens, jnp.int32),
                 jnp.asarray(pos, jnp.int32),
                 jnp.asarray(qlen, jnp.int32), jnp.asarray(active),
                 self.cache, self.rope, self.config)
        js = self._obs_jit("mixed_step", (C,), self._mixed_step_fn,
                           fargs)
        t0d = time.perf_counter()
        logits, self.cache = self._mixed_step_fn(*fargs)
        js.finish(time.perf_counter() - t0d)
        self._last_jit = js
        emit_rows = decode_rows + finished
        # advance the prefill frontiers BEFORE sampling/emit: a
        # finishing row's _pos must read prompt-end when _emit runs
        # its window-cap check (the _finish_prefill ordering)
        for slot in chunk_rows:
            p = self._mixed_pending[slot]
            p["off"] += int(qlen[slot])
            self._pos[slot] = p["off"]
        if emit_rows:
            nxt, lp, tids, tlps = self._sample_rows(
                logits, rows=emit_rows, n_top=self._n_top_for(emit_rows))
        else:
            # every row is mid-prompt: nothing samples this step — skip
            # the masked-sampling program entirely (its outputs would
            # all be discarded, and it sits on the TTFT path)
            nxt = lp = tids = tlps = None
        self.stats.steps += 1
        dt = time.perf_counter() - t0
        # split the step wall by TOKEN share so the prefill/decode
        # accounting stays meaningful under the mixed default (a mixed
        # step IS both phases in one launch; all-to-decode would report
        # prefill_time_s == 0 forever, and a per-row split would
        # undercount a C-token chunk against a 1-token decode row)
        chunk_toks = int(sum(qlen[s] for s in chunk_rows))
        total_toks = chunk_toks + len(decode_rows)
        pf = dt * chunk_toks / total_toks
        self.stats.prefill_time_s += pf
        self.stats.decode_time_s += dt - pf
        self._obs_paged_step("mixed", dt)
        self._record_step(
            "mixed", rows=len(decode_rows) + len(chunk_rows),
            tokens=len(emit_rows), wall_s=dt,
            rows_decode=len(decode_rows), rows_prefill=len(chunk_rows),
            rows_idle=B - len(decode_rows) - len(chunk_rows),
            rids=[r for r, _s in self._implicated])
        self._step_stats.step(bytes_out=len(emit_rows))

        def _top(slot):
            return (list(zip(tids[slot].tolist(), tlps[slot].tolist()))
                    if tids.size else [])

        for slot in decode_rows:
            req = self._slot_req[slot]
            if req is None:
                continue
            self._pos[slot] += 1
            self._emit(req, int(nxt[slot]), logprob=float(lp[slot]),
                       top=_top(slot))
        for slot in finished:
            p = self._mixed_pending.pop(slot, None)
            if p is None:
                continue
            self._emit(p["req"], int(nxt[slot]),
                       logprob=float(lp[slot]), top=_top(slot))

    def _match_and_validate_prefix(self, ids: List[int]):
        """(pid, (p_ids, k, v)) of the longest matching registered prefix
        that can serve this prompt without clamping over live cache
        entries, or None. Returns the ENTRY, not just the pid: a
        concurrent eviction (handler-thread auto-prefix FIFO) must not
        turn the engine thread's later lookup into a KeyError."""
        hit = self._match_prefix(ids)
        if hit is None:
            return None
        pid, p_ids, k, v = hit
        plan = self._prefix_window_plan(p_ids, ids)
        if plan is None:
            return None
        # LRU recency for the cold-prefix spill policy (host tier)
        self._prefix_last_hit[pid] = time.monotonic()
        return (pid, (p_ids, k, v))

    def _prefix_window_plan(self, p_ids: List[int], ids: List[int]):
        """(chunk_suffix, C_or_bucket) for a prefix-hit prefill, or None
        when the suffix windows would clamp over the live prefix. Pure
        function of (p_ids, ids, prefill_chunk, max_seq_len, engine
        flavor) — the coordinator decides with it and a multi-host
        follower re-derives the identical plan from the published op.

        One clamp rule for every engine: windows (or the padded
        single-program bucket) must never clamp over the live prefix.
        The pipelined engine ALWAYS windows the suffix at pos0 = P (it
        has no single-program prefixed-prefill variant); the dense and
        paged engines window only when --prefill-chunk applies, else
        take their single program (prefill_slot_prefixed /
        prefill_slot_paged_prefixed)."""
        C = self.prefill_chunk
        suffix = ids[len(p_ids):]
        # the paged engine has its own single-program prefixed prefill
        # (prefill_slot_paged_prefixed), so only a genuinely pipelined
        # custom path is forced through suffix windows
        pipelined = (self._prefill_slot is not prefill_slot
                     and not self.paged)
        if pipelined or (C and len(suffix) > C):
            Cw = C or bucket_length(len(suffix), self.max_seq_len)
            n_win = -(-len(suffix) // Cw)
            if len(p_ids) + n_win * Cw <= self.max_seq_len:
                return (True, Cw)
            return None   # last window would clamp over the prefix
        bucket = bucket_length(len(suffix), self.max_seq_len)
        if len(p_ids) + bucket > self.max_seq_len:
            # the padded window would clamp over the live prefix
            # (dynamic_update_slice clamps out-of-range starts)
            return None
        return (False, bucket)

    def _prefixed_prefill_device(self, pid: int, ids, slot: int,
                                 temp: float, top_p: float, penalty: float,
                                 prime, n_top: int = 0,
                                 entry=None, defer: bool = False) -> tuple:
        """Prefix-hit prefill: install the cached prefix KV, prefill only
        the suffix, sample the first token. Runs identically on the
        coordinator (which passes the matched `entry` so a concurrent
        eviction cannot invalidate the pid between match and use) and,
        via the prefill_prefixed op, every follower (which resolves the
        pid in its mirrored registry — safe by wire ordering: evictions
        arrive as unregister ops on this same thread)."""
        ids = list(ids)
        if entry is None:
            with self._rid_lock:
                entry = self._prefixes[pid]
        p_ids, pk, pv = entry
        plan = self._prefix_window_plan(p_ids, ids)
        if plan is None:  # cannot happen for a published op; be loud
            raise RuntimeError(
                f"prefix {pid} no longer serves prompt of len {len(ids)}")
        chunk_suffix, width = plan
        suffix = ids[len(p_ids):]
        _PREFIX_TOKENS_SAVED.inc(len(p_ids))
        if self.paged:
            # the shared prefix pages are ALREADY mapped at the head of
            # this slot's table row (_alloc_slot_pages) — no install
            # step at all. The suffix prefills through the paged
            # prefixed program (single window) or the paged chunk fn
            # (which attends everything written through the table,
            # prefix head included).
            _PREFIX_PAGED_HITS.inc()
            if chunk_suffix:
                logits = self._prefill_chunked(suffix, slot, width,
                                               pos0=len(p_ids))
            else:
                padded = suffix + [0] * (width - len(suffix))
                fargs = (self.params, jnp.asarray([padded], jnp.int32),
                         jnp.asarray([len(suffix)], jnp.int32),
                         jnp.int32(slot), self.cache, self.rope,
                         self.config)
                fkw = dict(n_prefix=len(p_ids))
                js = self._obs_jit("prefill_paged_prefixed",
                                   (width, len(p_ids)),
                                   self._paged_prefixed_step, fargs, fkw)
                t0 = time.perf_counter()
                logits, self.cache = self._paged_prefixed_step(*fargs,
                                                               **fkw)
                js.finish(time.perf_counter() - t0)
                self._last_jit = js
            return self._finish_prefill(logits, slot, len(ids), temp,
                                        top_p, penalty, prime,
                                        n_top=n_top, defer=defer)
        if chunk_suffix:
            from cake_tpu.models.llama.model import install_prefix_slot
            self.cache = install_prefix_slot(self.cache, pk, pv,
                                             jnp.int32(slot))
            logits = self._prefill_chunked(suffix, slot, width,
                                           pos0=len(p_ids))
        else:
            padded = suffix + [0] * (width - len(suffix))
            fargs = (self.params, jnp.asarray([padded], jnp.int32),
                     jnp.asarray([len(suffix)], jnp.int32),
                     jnp.int32(slot), pk, pv, self.cache, self.rope,
                     self.config)
            js = self._obs_jit("prefill_prefixed",
                               (width, int(pk.shape[2])),
                               prefill_slot_prefixed, fargs)
            t0 = time.perf_counter()
            logits, self.cache = prefill_slot_prefixed(*fargs)
            js.finish(time.perf_counter() - t0)
            self._last_jit = js
        return self._finish_prefill(logits, slot, len(ids), temp,
                                    top_p, penalty, prime, n_top=n_top,
                                    defer=defer)

    def _prefill_raw(self, ids, slot: int):
        """Whole-prompt prefill device call (no sampling-state changes)."""
        ids = list(ids)
        bucket = bucket_length(len(ids), self.max_seq_len)
        padded = ids + [0] * (bucket - len(ids))
        toks = jnp.asarray([padded], jnp.int32)
        plen = jnp.asarray([len(ids)], jnp.int32)
        fargs = (self.params, toks, plen, jnp.int32(slot), self.cache,
                 self.rope, self.config)
        js = self._obs_jit("prefill_slot", (bucket,),
                           self._prefill_slot, fargs)
        t0 = time.perf_counter()
        logits, self.cache = self._prefill_slot(*fargs)
        js.finish(time.perf_counter() - t0)
        self._last_jit = js
        if self._spec:
            # the draft's KV must cover the prompt too (its proposals
            # attend the same positions the target verifies)
            _, self.d_cache = self._prefill_slot(
                self.draft_params, toks, plen, jnp.int32(slot),
                self.d_cache, self.d_rope, self.draft_config,
            )
        return logits

    def _prefill_device(self, ids, slot: int, temp: float, top_p: float,
                        penalty: float, prime, n_top: int = 0,
                        defer: bool = False) -> tuple:
        """Prefill one slot (whole-prompt or chunked, decided from
        shared config + prompt length) + first-token sample: the
        device-and-mirror sequence of _do_prefill's non-prefix branch,
        replayed verbatim by multi-host followers (run_follower_loop) so
        the SPMD dispatch sequence cannot drift between processes."""
        ids = list(ids)
        C = self.prefill_chunk
        if C and (len(ids) > C or self.ring):
            # ring mode routes EVERY prompt through chunk windows — the
            # whole-bucket path would write past the ring capacity
            logits = self._prefill_chunked(ids, slot, C)
        else:
            logits = self._prefill_raw(ids, slot)
        return self._finish_prefill(logits, slot, len(ids), temp,
                                    top_p, penalty, prime, n_top=n_top,
                                    defer=defer)

    def _finish_prefill(self, logits, slot: int, prompt_len: int,
                        temp: float, top_p: float, penalty: float,
                        prime, n_top: Optional[int] = None,
                        defer: bool = False) -> tuple:
        """Configure the slot's sampling state and sample its first
        token. Returns (token_id, logprob, top-N alternatives), or the
        deferred device tuple when defer=True (_do_prefill_batch fetches
        it together with the whole admission wave's)."""
        if self._multihost:
            # replicated logits -> local host copy, so sampling is a
            # process-local computation (identical on every process by
            # determinism) instead of a cross-process collective
            logits = np.asarray(logits)
        self._pos[slot] = prompt_len
        self._temp[slot] = temp
        self._top_p[slot] = top_p
        self._penalty[slot] = penalty
        self._prime_ring(slot, prime)
        # sample the first token with the slot's own key/options
        sampled = self._sample_rows(
            jnp.broadcast_to(logits, (self.max_slots, logits.shape[-1])),
            rows=[slot], n_top=n_top, defer=defer)
        if defer:
            return sampled          # device tuple for _do_prefill_batch
        return self._finish_prefill_complete(slot, sampled,
                                             mirrors_done=True)

    def _finish_prefill_complete(self, slot: int, host,
                                 mirrors_done: bool = False) -> tuple:
        """Host half of _finish_prefill: mirror advance (unless
        _sample_rows already did it) + first-token unpack."""
        if not mirrors_done:
            host = self._sample_complete([slot], host)
        first, first_lp, tids, tlps = host
        top = (list(zip(tids[slot].tolist(), tlps[slot].tolist()))
               if tids.size else [])
        return int(first[slot]), float(first_lp[slot]), top

    def _prefill_chunked(self, ids: List[int], slot: int, C: int,
                         pos0: int = 0):
        """Walk a prompt (or a prefix-cache suffix starting at absolute
        position pos0) through slot `slot` in fixed C-token windows —
        the engine analog of the generator's --prefill-chunk path, using
        the same chunk_windows contract."""
        from cake_tpu.models.llama.generator import chunk_windows
        logits = None
        for window, n_real, start in chunk_windows(ids, C):
            fargs = (self.params, jnp.asarray([window], jnp.int32),
                     jnp.asarray([n_real], jnp.int32), jnp.int32(slot),
                     jnp.int32(pos0 + start), self.cache, self.rope,
                     self.config)
            js = self._obs_jit("prefill_chunk", (C,),
                               self._prefill_chunk_step, fargs)
            t0 = time.perf_counter()
            logits, self.cache = self._prefill_chunk_step(*fargs)
            js.finish(time.perf_counter() - t0)
            self._last_jit = js
        return logits

    @engine_thread_only
    def _do_decode_spec(self, decode_plan) -> None:
        """One propose-verify-accept round for ALL planned slots in ONE
        compiled program (speculative.spec_round_batched): batched
        ragged draft steps + one windowed verify pass, so the weights
        stream once per round instead of once per slot (the old
        per-slot spec_step_slot dispatches ran B batch-1 model passes —
        measured 29 tok/s aggregate at 8 streams on a v5e; batched
        rounds remove that B-times weight re-read). Speculation stays a
        latency feature; the engine's win is CONCURRENCY — many clients
        speculate together — plus API streaming and checkpoint/resume
        composition."""
        self._implicated = decode_plan
        if self._faults is not None:
            self._faults.check("engine.decode", step=self.stats.steps)
        from cake_tpu.models.llama.speculative import spec_round_batched

        t0 = time.perf_counter()
        g = self.spec_gamma
        B = self.max_slots
        plan = []
        for rid, slot in decode_plan:
            req = self._slot_req[slot]
            if req is None:
                continue
            if self._pos[slot] + g + 1 >= self.max_seq_len:
                # the round writes g+1 cache positions; too close to the
                # window end, finish at the cap (loses at most gamma
                # tokens of an already maxed-out context)
                self._force_finish(req)
                continue
            plan.append((req, slot))
        if not plan:
            self.stats.decode_time_s += time.perf_counter() - t0
            return
        active = np.zeros(B, bool)
        for _, slot in plan:
            active[slot] = True
        active_dev = jnp.asarray(active)
        temp_dev = jnp.asarray(self._temp)

        def dispatch(state):
            if state is None:
                last = jnp.asarray(self._last_tok[:, None], jnp.int32)
                pos = jnp.asarray(
                    np.minimum(self._pos, self.max_seq_len - 1),
                    jnp.int32)
            else:
                last, pos = state
            fargs = (self.params, self.draft_params, self.cache,
                     self.d_cache, last, pos, active_dev, self._keys,
                     temp_dev, self.rope, self.d_rope, self.config,
                     self.draft_config, g)
            js = self._obs_jit("spec_round", (g,), spec_round_batched,
                               fargs)
            t0d = time.perf_counter()
            (out, n_emit, self.cache, self.d_cache, self._keys,
             state_o) = spec_round_batched(*fargs)
            disp = time.perf_counter() - t0d
            js.finish(disp)
            return (out, n_emit, disp, js), state_o

        def complete(devs):
            out_d, n_emit_d, disp_k, js_k = devs
            # ONE batched fetch for every slot's round (a
            # remote-dispatch tunnel charges ~100ms per round-trip)
            t0f = time.perf_counter()
            out_h, n_emit_h = jax.device_get((out_d, n_emit_d))
            fetch = time.perf_counter() - t0f
            round_tokens = 0
            for req, slot in plan:
                if req.done.is_set():
                    # chained round dispatched before this req's EOS /
                    # budget end was known — discard its junk (stats
                    # too: post-EOS rounds condition on garbage)
                    continue
                n = int(n_emit_h[slot])
                round_tokens += n
                toks = [int(t) for t in out_h[slot, :n]]
                self.stats.spec_proposed += g
                self.stats.spec_accepted += n - 1
                pos0 = int(self._pos[slot])
                self._last_tok[slot] = toks[-1]
                self._steps[slot] += n
                for j, tok in enumerate(toks):
                    # per-token position so _emit's cap check sees the
                    # value a single-step loop would have had
                    # (_do_decode_scan precedent — the post-burst
                    # frontier would cap-finish the FIRST token of a
                    # window-filling burst)
                    self._pos[slot] = pos0 + j + 1
                    self._emit(req, tok)
                    if req.done.is_set():
                        break   # EOS / budget mid-burst: drop the tail
                # cache frontier for the next round: the burst wrote n
                # accepted positions regardless of the emission budget;
                # stale positions past it are masked like padding
                self._pos[slot] = pos0 + n
            self.stats.steps += 1
            self._record_step("spec", rows=len(plan),
                              tokens=round_tokens, dispatch_s=disp_k,
                              device_s=fetch, wall_s=disp_k + fetch,
                              js=js_k,
                              rids=[req.rid for req, _s in plan])

        # double-buffered chained rounds (single-host; multi-host spec
        # has no engine), via the shared _drive_burst driver: round k+1
        # is dispatched from round k's on-device state before round k's
        # tokens are fetched. The window guard projects the device
        # frontier by the worst case (g+1 per unfetched round); a round
        # chained past a row's EOS computes junk the emit loop
        # discards. The first round is unconditional: every planned row
        # was admitted with room for >= 1 round (the force-finish guard
        # above), and skipping it would leave the run loop spinning
        # with full slots and a waiting queue.
        def can_chain(n_inflight: int) -> bool:
            return (all(not req.done.is_set()
                        and (req.max_new_tokens - len(req.out_tokens)
                             - n_inflight * (g + 1)) > 0
                        for req, _ in plan)
                    and all(self._pos[s] + (n_inflight + 1) * (g + 1)
                            < self.max_seq_len for _, s in plan))

        self._drive_burst(dispatch, complete, can_chain,
                          first_unconditional=True)
        self.stats.decode_time_s += time.perf_counter() - t0

    def _force_finish(self, req: _Request) -> None:
        """Finish a request that cannot receive another token (spec
        window cap): the _emit finish tail, minus the token."""
        self.scheduler.report(req.rid, 0, True)
        req.finish_t = time.perf_counter()
        if req.slot >= 0 and self._slot_req[req.slot] is req:
            self._slot_req[req.slot] = None
        self._requests.pop(req.rid, None)
        if self._shed is not None:
            self._shed.observe_retire()
        self.stats.requests_completed += 1
        self._journal_retire(req, "retired")
        self.tracer.finish(req.rid, "retired",
                           output_tokens=len(req.out_tokens))
        if req.stream is not None:
            try:
                delta = self._incremental_text(req, final=True)
                if req.stream_wants_count:
                    req.stream(delta, True, len(req.out_tokens))
                else:
                    req.stream(delta, True)
            except Exception:  # noqa: BLE001
                log.exception("stream callback failed rid=%d", req.rid)
        req.done.set()

    # -- paged speculative decoding (cake_tpu/spec) ---------------------------

    @engine_thread_only
    def _do_spec_paged(self, decode_plan):
        """One batched draft+verify round over PAGED KV for this
        iteration's spec-eligible decode rows; returns the rows the
        round did NOT cover (the caller's plain decode paths take
        them). Page discipline per row and round: extend BOTH table
        rows to cover pos..pos+gamma before dispatch (spec_round_paged
        writes gamma+1 positions in each pool; writes past the mapped
        pages silently drop, which would zero an ACCEPTED position's
        KV), then truncate back to the accepted frontier after the
        fetch — `free_pages + live_pages == n_pages` holds again before
        the method returns."""
        if self._specp is None:
            return decode_plan
        from cake_tpu.sched import partition_rows
        g = self._specp.live_gamma
        spec_rows, plain = partition_rows(
            decode_plan, lambda rid, slot: self._spec_row_ready(rid, slot, g))
        if not spec_rows:
            return plain
        t0 = time.perf_counter()
        plan = []
        for rid, slot in spec_rows:
            if self._spec_extend_rows(slot, g):
                plan.append((self._slot_req[slot], slot))
            else:
                # pool pressure mid-flight: the row decodes plain this
                # iteration and tries again when pages free up
                plain.append((rid, slot))
        if not plan:
            self.stats.decode_time_s += time.perf_counter() - t0
            return plain
        # chaos site for the verify pass — the kv.ship failure
        # discipline: an INJECTED verify fault is absorbed here
        # (penalize the rows' acceptance signal, truncate their
        # extensions, degrade repeat offenders, decode plain this
        # iteration); organic dispatch errors below still propagate to
        # the recovery path with the round's rows implicated
        if self._faults is not None:
            try:
                self._faults.check("spec.verify", step=self.stats.steps)
            except Exception as exc:  # noqa: BLE001 — injected faults
                from cake_tpu.faults.plan import InjectedFault
                if not isinstance(exc, InjectedFault):
                    raise
                self._spec_verify_failed(plan, g, exc)
                self.stats.decode_time_s += time.perf_counter() - t0
                return plain + [(req.rid, s) for req, s in plan]
        self._implicated = tuple((req.rid, s) for req, s in plan)
        sp = self._specp
        active = np.zeros(self.max_slots, bool)
        for _req, slot in plan:
            active[slot] = True
        last = jnp.asarray(self._last_tok[:, None], jnp.int32)
        pos = jnp.asarray(np.minimum(self._pos, self.max_seq_len - 1),
                          jnp.int32)
        fargs = (self.params, sp.draft_params, self.cache, self.d_cache,
                 last, pos, jnp.asarray(active), self._keys,
                 jnp.asarray(self._temp), self.rope, sp.rope,
                 self.config, sp.draft_config, g)
        js = self._obs_jit("spec_round_paged", (g,),
                           self._spec_round_fn, fargs)
        t0d = time.perf_counter()
        (out, n_emit, self.cache, self.d_cache,
         self._keys) = self._spec_round_fn(*fargs)
        disp = time.perf_counter() - t0d
        js.finish(disp)
        # ONE batched fetch for every row's round
        t0f = time.perf_counter()
        out_h, n_emit_h = jax.device_get((out, n_emit))
        fetch = time.perf_counter() - t0f
        round_tokens = proposed = accepted = 0
        for req, slot in plan:
            if req.done.is_set():
                continue
            n = int(n_emit_h[slot])
            round_tokens += n
            proposed += g
            accepted += n - 1
            toks = [int(t) for t in out_h[slot, :n]]
            self.stats.spec_proposed += g
            self.stats.spec_accepted += n - 1
            pos0 = int(self._pos[slot])
            self._last_tok[slot] = toks[-1]
            self._steps[slot] += n
            for j, tok in enumerate(toks):
                # per-token position so _emit's cap check sees the
                # value a single-step loop would have had
                self._pos[slot] = pos0 + j + 1
                self._emit(req, tok)
                if req.done.is_set():
                    break   # EOS / budget mid-round: drop the tail
            # cache frontier for the next round: the round wrote n
            # accepted positions regardless of the emission budget
            self._pos[slot] = pos0 + n
            # a finished row's _emit tail already tore its spec state
            # down with the slot (zero leaked suffix pages); for live
            # rows, fold the round into the stream's controller signal
            # and give the unaccepted suffix pages back
            st = sp.spec_streams.get(slot)
            if st is not None and st.enabled:
                st.verify_fails = 0
                st.note_round(g, n - 1)
                self._spec_truncate(slot)
                from cake_tpu.spec.state import (
                    STREAM_ACCEPT_FLOOR, STREAM_WARMUP_ROUNDS,
                )
                if (st.rounds >= STREAM_WARMUP_ROUNDS
                        and (st.accept_ema or 0.0) < STREAM_ACCEPT_FLOOR):
                    self._spec_disable(req, slot, "acceptance_collapse")
        self.stats.steps += 1
        sp.note_round(proposed, accepted, round_tokens, len(plan))
        if self._specp.tuner is not None:
            ng = self._specp.tuner.maybe_shrink()
            if ng is not None and ng < self._specp.live_gamma:
                from cake_tpu.spec.state import SPEC_DEGRADED
                self._specp.live_gamma = ng
                SPEC_DEGRADED.labels(action="shrink_gamma").inc()
                log.warning("spec: acceptance EMA %.2f below tuner "
                            "threshold — gamma shrunk to %d",
                            self._specp.accept_ema or 0.0, ng)
                if self.events is not None:
                    self.events.publish(
                        "spec_degraded", action="shrink_gamma",
                        gamma=ng, accept_ema=self._specp.accept_ema)
        if self.events is not None:
            self.events.publish("spec_round", rows=len(plan),
                                proposed=proposed, accepted=accepted,
                                tokens=round_tokens, gamma=g)
        self._record_step("spec", rows=len(plan), tokens=round_tokens,
                          dispatch_s=disp, device_s=fetch,
                          wall_s=disp + fetch, js=js,
                          rids=[req.rid for req, _s in plan])
        self.stats.decode_time_s += time.perf_counter() - t0
        return plain

    def _spec_row_ready(self, rid: int, slot: int, g: int) -> bool:
        """Is this decode row riding THIS iteration's speculative
        round? Temperature-only sampling (top-p / repetition-penalty /
        top-logprobs rows replay exactly on the plain path — dense-spec
        submit() rejects them, the paged engine just declines per row),
        window room for a whole round, >= 1 emitted token (the round
        contract wants last_tok's KV unwritten at the decode frontier),
        and an enabled SpecState — activated lazily here, whatever path
        brought the stream to its frontier (whole/chunked/prefix
        prefill, preemption resume, recovery replay)."""
        if self._specp is None:
            return False
        req = self._slot_req[slot]
        if req is None or req.rid != rid or req.done.is_set():
            return False
        if not req.out_tokens:
            return False
        if req.top_p < 1.0 or req.repeat_penalty != 1.0 or req.want_top:
            return False
        if self._pos[slot] + g + 1 >= self.max_seq_len:
            # too close to the window: the plain path finishes the
            # stream at the cap (no dense-style _force_finish — the
            # row loses speculation, not its tail tokens)
            return False
        st = self._specp.spec_streams.get(slot)
        if st is not None and st.rid != req.rid:
            # defensive: a slot reused without the teardown hook (not a
            # known path) must not speculate against a stale draft row
            self._release_spec_state(slot)
            st = None
        if st is None:
            return self._spec_activate(req, slot)
        return st.enabled

    def _spec_activate(self, req: _Request, slot: int) -> bool:
        """Opt a decoding stream into speculation: allocate the draft
        row's context pages from the SHARED allocator and run one
        whole-context draft prefill, leaving the draft pool with KV for
        positions 0..pos-1 — exactly the round contract (the last
        emitted token's KV unwritten in both pools). Best-effort: any
        shortfall keeps the row on plain decode (False)."""
        if self._specp is None:
            return False
        from cake_tpu.models.llama.paged import table_set_slot
        pos = int(self._pos[slot])
        ctx = (list(req.prompt_ids) + list(req.out_tokens))[:pos]
        if len(ctx) != pos:
            return False   # frontier/transcript mismatch: stay plain
        d_pages = self._pager.alloc(len(ctx))
        if d_pages is None:
            return False   # pool pressure: retry on a later iteration
        from cake_tpu.spec import SpecState
        self._specp.spec_streams[slot] = SpecState(rid=req.rid,
                                             d_pages=d_pages)
        self.d_cache = self.d_cache._replace(
            table=table_set_slot(self.d_cache.table, slot, d_pages))
        bucket = bucket_length(len(ctx), self.max_seq_len)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :len(ctx)] = ctx
        sp = self._specp
        fargs = (sp.draft_params, jnp.asarray(toks),
                 jnp.asarray([len(ctx)], jnp.int32), jnp.int32(slot),
                 self.d_cache, sp.rope, sp.draft_config)
        js = self._obs_jit("spec_draft_prefill", (bucket,),
                           self._prefill_slot, fargs)
        t0 = time.perf_counter()
        _logits, self.d_cache = self._prefill_slot(*fargs)
        js.finish(time.perf_counter() - t0)
        self._last_jit = js
        return True

    def _spec_extend_rows(self, slot: int, g: int) -> bool:
        """Pre-round page extension: both table rows must cover
        positions pos..pos+gamma before dispatch. The draft row is one
        list in its SpecState; the target row is the admission base
        (the engine's `_slot_pages` + shared prefix, untouched here)
        plus the state's suffix-extension pages. False = the pool
        cannot cover the round; the row decodes plain this iteration
        (whatever WAS extended stays until its post-round truncation
        or teardown — conservation holds either way)."""
        if self._specp is None:
            return False
        from cake_tpu.models.llama.paged import table_set_slot
        st = self._specp.spec_streams[slot]
        ps = self.cache.page_size
        cover = int(self._pos[slot]) + g + 1
        if cover > len(st.d_pages) * ps:
            extra = self._pager.alloc(cover - len(st.d_pages) * ps)
            if extra is None:
                return False
            st.d_pages = st.d_pages + extra
            self.d_cache = self.d_cache._replace(
                table=table_set_slot(self.d_cache.table, slot,
                                     st.d_pages))
        base = self._slot_row_pages(slot)
        have = (len(base) + len(st.t_suffix_pages)) * ps
        if cover > have:
            extra = self._pager.alloc(cover - have)
            if extra is None:
                return False
            st.t_suffix_pages = st.t_suffix_pages + extra
            self.cache = self.cache._replace(
                table=table_set_slot(self.cache.table, slot,
                                     base + st.t_suffix_pages))
        return True

    def _slot_row_pages(self, slot: int) -> list:
        """A slot's BASE target row (shared prefix pages + its own
        admission pages, in the table order _alloc_slot_pages mapped) —
        the part of the target row spec never owns."""
        return list(self._slot_pages.get(slot, []))

    def _spec_truncate(self, slot: int) -> None:
        """Acceptance truncation: give back every speculative page past
        the accepted frontier — the draft row shrinks to its context
        coverage, the target row to whatever its base allocation does
        not already cover — and remap the shrunk table rows. After this
        the allocator invariant `free_pages + live_pages == n_pages`
        holds with zero pages parked for rejected drafts."""
        if self._specp is None:
            return
        st = self._specp.spec_streams.get(slot)
        if st is None:
            return
        from cake_tpu.models.llama.paged import table_set_slot
        need = self._pager.pages_for(int(self._pos[slot]))
        keep = max(need, 1)     # a decoding row always keeps a page
        if keep < len(st.d_pages):
            self._pager.release(st.d_pages[keep:])
            st.d_pages = st.d_pages[:keep]
            self.d_cache = self.d_cache._replace(
                table=table_set_slot(self.d_cache.table, slot,
                                     st.d_pages))
        base = self._slot_row_pages(slot)
        keep_sfx = max(need - len(base), 0)
        if keep_sfx < len(st.t_suffix_pages):
            self._pager.release(st.t_suffix_pages[keep_sfx:])
            st.t_suffix_pages = st.t_suffix_pages[:keep_sfx]
            self.cache = self.cache._replace(
                table=table_set_slot(self.cache.table, slot,
                                     base + st.t_suffix_pages))

    def _spec_disable(self, req: _Request, slot: int,
                      reason: str) -> None:
        """Per-stream degrade to plain decode — never wedge: release
        every speculative page back to the pool, keep a disabled
        tombstone so the stream is not re-activated, and publish the
        degrade. The stream itself keeps decoding on the plain path
        with its base pages untouched."""
        if self._specp is None:
            return
        st = self._specp.spec_streams.get(slot)
        if st is None or not st.enabled:
            return
        from cake_tpu.models.llama.paged import table_set_slot
        from cake_tpu.spec.state import SPEC_DEGRADED
        if st.d_pages:
            self._pager.release(st.d_pages)
            st.d_pages = []
        if st.t_suffix_pages:
            self._pager.release(st.t_suffix_pages)
            st.t_suffix_pages = []
            self.cache = self.cache._replace(
                table=table_set_slot(self.cache.table, slot,
                                     self._slot_row_pages(slot)))
        st.enabled = False
        SPEC_DEGRADED.labels(action="disabled").inc()
        log.warning("spec: rid=%d degraded to plain decode (%s, "
                    "accept_ema=%.2f after %d rounds)", req.rid, reason,
                    st.accept_ema or 0.0, st.rounds)
        if self.events is not None:
            self.events.publish("spec_degraded", rid=req.rid,
                                action="disabled", reason=reason,
                                accept_ema=st.accept_ema,
                                rounds=st.rounds)

    def _spec_verify_failed(self, plan, g: int, exc) -> None:
        """An injected spec.verify fault: charge a zero-acceptance
        round to every planned row (the controller sees collapse, not
        silence), truncate their pre-round extensions back, and disable
        repeat offenders — the PR-19 kv.ship discipline: degrade, never
        wedge, and the rows finish on the plain path either way."""
        if self._specp is None:
            return
        from cake_tpu.spec.state import DISABLE_AFTER_FAILS
        log.warning("spec.verify fault (%s): %d rows decode plain this "
                    "iteration", exc, len(plan))
        for req, slot in plan:
            st = self._specp.spec_streams.get(slot)
            if st is None or not st.enabled:
                continue
            st.verify_fails += 1
            st.note_round(g, 0)
            self._spec_truncate(slot)
            if st.verify_fails >= DISABLE_AFTER_FAILS:
                self._spec_disable(req, slot, "verify_faults")
        self._specp.note_round(g * len(plan), 0, 0, len(plan))
        if self.events is not None:
            self.events.publish("spec_round", rows=len(plan),
                                proposed=g * len(plan), accepted=0,
                                tokens=0, gamma=g, fault=True)

    @engine_thread_only
    def _do_decode(self, decode_plan) -> None:
        t0 = time.perf_counter()
        self._implicated = decode_plan
        if self._faults is not None:
            self._faults.check("engine.decode", step=self.stats.steps)
        rows = [s for _, s in decode_plan]
        n_top = self._n_top_for(rows)
        self._publish({"op": "decode", "rows": rows, "n_top": n_top})
        nxt, lp, tids, tlps = self._decode_device(rows, n_top=n_top)
        self.stats.steps += 1
        dt = time.perf_counter() - t0
        self.stats.decode_time_s += dt
        self._obs_paged_step("decode", dt)
        self._record_step("decode", rows=len(decode_plan),
                          tokens=len(decode_plan), wall_s=dt,
                          rids=[r for r, _s in decode_plan])
        self._step_stats.step(bytes_out=len(decode_plan))
        for rid, slot in decode_plan:
            req = self._slot_req[slot]
            if req is None or req.rid != rid:
                continue
            self._emit(req, int(nxt[slot]), logprob=float(lp[slot]),
                       top=(list(zip(tids[slot].tolist(),
                                     tlps[slot].tolist()))
                            if tids.size else []))

    def _decode_device(self, rows, n_top: Optional[int] = None) -> tuple:
        """One ragged decode step + sample for the given slot rows: the
        device-and-mirror half of _do_decode, shared verbatim by the
        coordinator and multi-host followers."""
        B = self.max_slots
        active = np.zeros(B, bool)
        for slot in rows:
            active[slot] = True
        toks = jnp.asarray(self._last_tok[:, None], jnp.int32)
        pos = jnp.asarray(np.minimum(self._pos, self.max_seq_len - 1),
                          jnp.int32)
        fargs = (self.params, toks, pos, jnp.asarray(active), self.cache,
                 self.rope, self.config)
        js = self._obs_jit("decode_step", (), self._decode_step, fargs)
        t0 = time.perf_counter()
        logits, self.cache = self._decode_step(*fargs)
        js.finish(time.perf_counter() - t0)
        self._last_jit = js
        if self._multihost:
            logits = np.asarray(logits)  # see _finish_prefill
        nxt, lp, tids, tlps = self._sample_rows(logits, rows=rows,
                                                n_top=n_top)
        self._pos += active  # only active rows advanced
        return nxt, lp, tids, tlps

    def _scan_steps_for(self, decode_plan) -> int:
        """Fixed scan length when multi-step decode is safe right now:
        nobody queued (a waiting request must not see its admission
        delayed by a whole scan) and K more cache writes fit every
        row's window. Rows with under K tokens of max_new_tokens budget
        are fine — the device program freezes each row at its per-row
        budget (make_decode_scan), so the scan cannot overshoot."""
        n = self._decode_scan
        if n <= 1 or self.scheduler.queue_depth > 0:
            return 1
        max_left = 0
        for _, slot in decode_plan:
            req = self._slot_req[slot]
            if req is None:
                return 1
            max_left = max(max_left,
                           req.max_new_tokens - len(req.out_tokens))
            if self._pos[slot] + n >= self.max_seq_len:
                return 1
        # per-row budget freeze (make_decode_scan) makes a scan safe for
        # rows with < n budget; only when EVERY row is on its last token
        # is the single-step program the cheaper dispatch
        if max_left <= 1:
            return 1
        return n

    def _scan_budget(self, decode_plan, n: int,
                     shipped: Optional[dict] = None) -> np.ndarray:
        """Per-row token allowance for one n-step scan: the request's
        remaining max_new_tokens budget, minus tokens already dispatched
        in not-yet-fetched chained scans (`shipped`), capped at n. Rows
        with 0 allowance are frozen by the device program."""
        budget = np.zeros(self.max_slots, np.int32)
        for _, slot in decode_plan:
            req = self._slot_req[slot]
            if req is None:
                continue
            left = req.max_new_tokens - len(req.out_tokens)
            if shipped:
                left -= shipped.get(slot, 0)
            budget[slot] = max(0, min(n, left))
        return budget

    def _do_decode_scan(self, decode_plan, n: int) -> None:
        """n ragged decode steps + sampling as one compiled program
        (synchronous: dispatch, fetch, emit — the multi-host lockstep
        path; single-host serving uses _decode_burst instead)."""
        t0 = time.perf_counter()
        self._implicated = decode_plan
        if self._faults is not None:
            self._faults.check("engine.decode", step=self.stats.steps)
        rows = [s for _, s in decode_plan]
        n_top = self._n_top_for(rows)
        budget = self._scan_budget(decode_plan, n)
        # n_top must ride the op: in a multi-host scan the sampling is
        # INSIDE the mesh program, so a follower compiling the n_top=0
        # variant while the coordinator runs n_top=20 would dispatch a
        # different program and wedge the collective. budget rides it
        # for the same reason followers cannot derive it (no requests).
        self._publish({"op": "decode_scan", "rows": rows, "n": n,
                       "n_top": n_top, "budget": budget.tolist()})
        outs, _state = self._dispatch_scan_device(rows, n, n_top, budget)
        fetched = self._fetch_scan(outs)
        self.stats.steps += n
        dt = time.perf_counter() - t0
        self.stats.decode_time_s += dt
        self._obs_paged_step("decode", dt / n)
        self._record_step("decode_scan", rows=len(decode_plan),
                          tokens=int(budget.sum()), wall_s=dt,
                          rids=[r for r, _s in decode_plan])
        self._complete_scan(decode_plan, n, fetched, budget)

    def _decode_burst(self, decode_plan, n: int) -> None:
        """Double-buffered chained scans: dispatch scan k+1 (its inputs
        chained on device from scan k's final carry — zero host
        round-trips between scans) BEFORE fetching scan k's tokens, so
        the ~100ms d2h fetch latency of a remote-dispatch tunnel hides
        under scan k+1's device compute. Single-host only: a follower
        rebuilds scan inputs from its mirrors, which match the chained
        carry for live rows but diverge for rows that froze (EOS) inside
        an earlier not-yet-fetched scan — lockstep multi-host serving
        keeps the synchronous _do_decode_scan path instead."""
        t0 = time.perf_counter()
        self._implicated = decode_plan
        if self._faults is not None:
            self._faults.check("engine.decode", step=self.stats.steps)
        rows = [s for _, s in decode_plan]
        n_top = self._n_top_for(rows)
        # tokens dispatched in not-yet-fetched scans, per slot: added at
        # dispatch, removed at fetch — budget math and the window guard
        # both project the device state past the stale host mirrors by
        # exactly this amount
        shipped: dict = {}

        def can_chain(_n_inflight) -> bool:
            # real work remains, and the PROJECTED device position
            # (host mirror + unfetched in-flight tokens) still fits the
            # window: the mirror lags the device by the in-flight
            # scans, and the device program has no max_seq freeze.
            # (The per-slot `shipped` dict is finer-grained than the
            # driver's in-flight count, so the latter goes unused.)
            return (self._scan_budget(decode_plan, n, shipped).any()
                    and all(self._pos[s] + shipped.get(s, 0) + n
                            < self.max_seq_len for s in rows))

        def dispatch(state):
            # recomputed rather than smuggled out of can_chain: nothing
            # host-side changes between the gate and the dispatch (same
            # thread), and an explicit recompute keeps _drive_burst's
            # can_chain a pure gate
            budget = self._scan_budget(decode_plan, n, shipped)
            t0d = time.perf_counter()
            outs, state = self._dispatch_scan_device(
                rows, n, n_top, budget, state=state)
            disp = time.perf_counter() - t0d
            js, self._last_jit = self._last_jit, None
            for _, slot in decode_plan:
                shipped[slot] = shipped.get(slot, 0) + int(budget[slot])
            self.stats.steps += n
            return (outs, budget, disp, js), state

        def complete(devs):
            outs_k, budget_k, disp_k, js_k = devs
            t0f = time.perf_counter()
            fetched = self._fetch_scan(outs_k)
            fetch = time.perf_counter() - t0f
            # one record per scan: its own dispatch wall (this scan's
            # trace+enqueue) and the fetch wall as the device-side proxy
            self._record_step("decode_scan", rows=len(rows),
                              tokens=int(budget_k.sum()),
                              dispatch_s=disp_k, device_s=fetch,
                              wall_s=disp_k + fetch, js=js_k,
                              rids=[r for r, _s in decode_plan])
            self._complete_scan(decode_plan, n, fetched, budget_k)
            for _, slot in decode_plan:
                shipped[slot] = (shipped.get(slot, 0)
                                 - int(budget_k[slot]))

        steps0 = self.stats.steps
        self._drive_burst(dispatch, complete, can_chain)
        dt = time.perf_counter() - t0
        self.stats.decode_time_s += dt
        self._obs_paged_step("decode",
                             dt / max(1, self.stats.steps - steps0))

    def _complete_scan(self, decode_plan, n: int, fetched,
                       budget) -> None:
        """Emit one fetched scan's tokens and advance the host mirrors.
        A row emits min(its budget, EOS cut) tokens; the device program
        froze it at exactly that point (budget freeze + EOS freeze in
        make_decode_scan), so mirrors advance by the emitted count."""
        toks_host, lps_host, tops_i_host, tops_l_host = fetched
        self._step_stats.step(bytes_out=int(budget.sum()))
        for rid, slot in decode_plan:
            req = self._slot_req[slot]
            if req is None or req.rid != rid:
                continue
            pos0 = int(self._pos[slot])
            b = int(budget[slot])
            emitted = 0
            for j in range(b):
                # per-token position so _emit's cap check sees the value a
                # single-step loop would have had
                self._pos[slot] = pos0 + j + 1
                emitted = j + 1
                self._last_tok[slot] = toks_host[slot, j]
                self._emit(req, int(toks_host[slot, j]),
                           logprob=float(lps_host[slot, j]),
                           top=(list(zip(tops_i_host[slot, j].tolist(),
                                         tops_l_host[slot, j].tolist()))
                                if tops_i_host.size else []))
                if req.done.is_set():
                    # EOS/budget: the device froze the row here too
                    break
            self._steps[slot] += emitted
            self._pos[slot] = pos0 + emitted

    def _dispatch_scan_device(self, rows, n: int, n_top: int, budget,
                              state=None):
        """Device dispatch half of a K-step scan, shared verbatim with
        multi-host followers (via _decode_scan_device). In multi-host
        mode keys/ring are localized around the call (host numpy in,
        replicated output localized), so the surrounding single-step ops
        keep their process-local sampling while the scan itself runs
        sampling inside the mesh program identically on every process.
        state: a previous scan's final carry to chain from (single-host
        bursts); None rebuilds the inputs from the host mirrors."""
        B = self.max_slots
        if state is None:
            active = np.zeros(B, bool)
            for slot in rows:
                active[slot] = True
            last_tok = jnp.asarray(self._last_tok, jnp.int32)
            pos = jnp.asarray(np.minimum(self._pos, self.max_seq_len - 1),
                              jnp.int32)
            steps = jnp.asarray(self._steps, jnp.int32)
            active = jnp.asarray(active)
        else:
            last_tok, pos, steps, active = state
        keys, ring = self._keys, self._ring
        if self._multihost:
            keys, ring = np.asarray(keys), np.asarray(ring)
        fargs = (self.params, last_tok, pos, active, self.cache,
                 self.rope, self.config, keys, ring, steps,
                 jnp.asarray(self._temp), jnp.asarray(self._top_p),
                 jnp.asarray(self._penalty),
                 jnp.asarray(budget, jnp.int32))
        fkw = dict(num_steps=n, top_k=self.defaults.top_k, n_top=n_top)
        js = self._obs_jit("decode_scan", (n, n_top),
                           self._decode_scan_impl, fargs, fkw)
        t0 = time.perf_counter()
        (toks, lps, tops_i, tops_l, self.cache, keys_o, ring_o,
         state_o) = self._decode_scan_impl(*fargs, **fkw)
        js.finish(time.perf_counter() - t0)
        self._last_jit = js
        if self._multihost:
            keys_h, ring_h = jax.device_get((keys_o, ring_o))
            keys_o, ring_o = jnp.asarray(keys_h), jnp.asarray(ring_h)
        self._keys, self._ring = keys_o, ring_o
        return (toks, lps, tops_i, tops_l), state_o

    @staticmethod
    def _fetch_scan(outs) -> tuple:
        # ONE batched fetch: sequential np.asarray calls each pay a full
        # host<->device round-trip (~100ms over a remote-dispatch
        # tunnel, measured), so four of them would quadruple the
        # per-scan dispatch overhead
        return jax.device_get(outs)

    def _decode_scan_device(self, rows, n: int, n_top: int,
                            budget=None) -> tuple:
        """Synchronous dispatch+fetch (follower replay path)."""
        if budget is None:
            budget = np.full(self.max_slots, n, np.int32)
        outs, _state = self._dispatch_scan_device(
            rows, n, n_top, np.asarray(budget, np.int32))
        return self._fetch_scan(outs)

    def _finalize_scan_mirrors(self, rows, n: int, toks_host,
                               budget=None) -> None:
        """Follower-side mirror advance after a replayed scan. MUST
        agree with the coordinator's emit loop in _complete_scan: a row
        ends at min(its budget, EOS cut) — exactly where the device
        program froze it (budget freeze + EOS freeze in
        make_decode_scan)."""
        eos = self.config.eos_token_ids
        for slot in rows:
            pos0 = int(self._pos[slot])
            b = n if budget is None else int(budget[slot])
            end = b
            for j in range(b):
                if int(toks_host[slot, j]) in eos:
                    end = j + 1
                    break
            self._steps[slot] += end
            if end:
                self._last_tok[slot] = toks_host[slot, end - 1]
            self._pos[slot] = pos0 + end

    def _n_top_for(self, rows) -> int:
        """cap when any of the rows' requests asked for top_logprobs,
        else 0 (both variants are separately compiled and cached; on a
        follower no requests exist, so this is always 0 — safe, because
        multi-host sampling is process-local, not a collective)."""
        for r in rows:
            req = self._slot_req[r]
            if req is not None and req.want_top:
                return self.n_top
        return 0

    def _sample_rows(self, logits, rows: List[int],
                     n_top: Optional[int] = None, defer: bool = False):
        """Sample all B rows; advance keys/ring only for `rows` (so an
        inactive slot's PRNG stream is untouched). n_top: explicit value
        in multi-host replay (it rides every op so coordinator and
        followers compile the SAME sampling program — different n_top
        variants may fuse differently and flip a sampled token near a
        top-p boundary); None derives it from the rows' requests.
        defer=True returns the device tuple without fetching (the
        caller batches the fetch and runs _sample_complete itself)."""
        B = self.max_slots
        row_mask = np.zeros(B, bool)
        for r in rows:
            row_mask[r] = True
        nxt, self._keys, self._ring, lp, top_ids, top_lps = _masked_sample(
            jnp.asarray(row_mask), self._keys, logits, self._ring,
            jnp.asarray(self._steps, jnp.int32),
            jnp.asarray(self._temp), jnp.asarray(self._top_p),
            jnp.asarray(self._penalty), top_k=self.defaults.top_k,
            n_top=self._n_top_for(rows) if n_top is None else n_top,
        )
        dev = (nxt, lp, top_ids, top_lps)
        if defer:
            return dev
        # one batched fetch, not four sequential round-trips (see
        # _decode_scan_device)
        return self._sample_complete(rows, jax.device_get(dev))

    def _sample_complete(self, rows: List[int], host) -> tuple:
        """Host half of _sample_rows: advance the sampled rows' step and
        last-token mirrors from the (already fetched) host tuple."""
        nxt_host, lp_h, tids_h, tlps_h = host
        for r in rows:
            self._steps[r] += 1
            self._last_tok[r] = nxt_host[r]
        return (nxt_host, lp_h, tids_h, tlps_h)

    # -- token plumbing -------------------------------------------------------

    def _emit(self, req: _Request, token_id: int,
              logprob: float = 0.0, top=None) -> None:
        now = time.perf_counter()
        req.out_logprobs.append(logprob)
        req.out_top.append(top or [])
        if not req.out_tokens:
            req.first_token_t = now
            self.tracer.first_token(req.rid)
            # per-class TTFT (includes queue wait and any
            # preemption-induced requeues): the latency the SLO
            # scheduler exists to protect, labeled so interactive and
            # batch distributions separate on one scrape
            _SCHED_TTFT.labels(req.priority).observe(now - req.submit_t)
        else:
            self.tracer.token(req.rid)
        req.out_tokens.append(token_id)
        if req.crash_count:
            # a step that emits for this request succeeded: the crash
            # implication is no longer CONSECUTIVE — forgiven
            req.crash_count = 0
        if self._journal is not None:
            # buffered; one emit record per (request, iteration) lands
            # at the run loop's flush. The count is ABSOLUTE (replayed
            # prior generations included) — the SSE event-id coordinate
            self._journal.note_emit(
                req.rid, token_id,
                len(req.replayed_tokens) + len(req.out_tokens))
        self.stats.tokens_generated += 1
        eos = token_id in self.config.eos_token_ids
        hit_cap = (self._pos[req.slot] + 1 >= self.max_seq_len)
        finished = self.scheduler.report(req.rid, 1, eos or hit_cap)
        if req.stream is not None:
            # final=finished: flush any held-back UTF-8 tail — a stream
            # ending on an incomplete sequence would otherwise deliver
            # less text than the buffered response for the same request
            delta = self._incremental_text(req, final=finished)
            if delta or finished:
                try:
                    if req.stream_wants_count:
                        req.stream(delta, finished, len(req.out_tokens))
                    else:
                        req.stream(delta, finished)
                except Exception:  # noqa: BLE001
                    log.exception("stream callback failed rid=%d", req.rid)
        if finished:
            req.finish_t = now
            if req.ship_sink is not None:
                # disaggregated prefill host: fetch the slot's written
                # pages BEFORE release frees them — the sink queues the
                # shipment for the transfer channel's writer thread
                self._capture_shipment(req)
            self._slot_req[req.slot] = None
            self._release_slot_pages(req.slot)
            self._requests.pop(req.rid, None)
            self.stats.requests_completed += 1
            if self._shed is not None:
                self._shed.observe_retire()
            self._journal_retire(req, "retired")
            self.tracer.finish(req.rid, "retired",
                               output_tokens=len(req.out_tokens))
            req.done.set()

    def _incremental_text(self, req: _Request, final: bool = False) -> str:
        ids = [t for t in req.out_tokens
               if t not in self.config.eos_token_ids]
        new, req._pending_text = incremental_decode(
            self.tokenizer, ids, req._pending_text, final=final)
        return new

    def _fail_all(self, err: Exception, snapshot: bool = False) -> None:
        # beat-the-reference failure handling (the reference is fail-stop
        # with total state loss, client.rs:50-59): on a FATAL failure,
        # snapshot the in-flight requests BEFORE failing them, so a
        # restarted cluster resumes every interrupted generation
        # token-exact (serve/checkpoint resume semantics) instead of
        # losing them with the process. snapshot=True only from fatal
        # paths (heartbeat loss, a failure the engine cannot reset from)
        # — a transient reset-and-continue error must not leave a stale
        # snapshot that resurrects long-errored requests after a later
        # unclean exit.
        from cake_tpu.serve.errors import as_engine_error
        # clients always see the TYPED form: a retryable engine reset
        # maps to 503 + Retry-After at the API instead of a bare 500
        err = as_engine_error(err)
        with self._ckpt_lock:
            if snapshot:
                self._snapshot_before_fail()
            # claim the registry under the lock (two racing _fail_all
            # callers — health monitor + signal handler — each fail a
            # disjoint set), but run the per-request teardown OUTSIDE
            # it: _journal_retire takes _rid_lock, and the declared
            # lock order (_rid_lock before _ckpt_lock) forbids
            # acquiring it while _ckpt_lock is held
            doomed = []
            for rid in list(self._requests):
                req = self._requests.pop(rid, None)
                if req is not None:
                    doomed.append((rid, req))
        for rid, req in doomed:
            req.error = err
            self.scheduler.cancel(rid)
            with self._rid_lock:
                self._adopt_store.pop(rid, None)
            if self._host_tier is not None:
                self._host_tier.drop(("victim", rid))
            if req.slot >= 0:
                # cakelint: skip[affinity] fatal path: the engine thread is wedged or has exited; cross-thread teardown is deliberate
                self._slot_req[req.slot] = None
                self._release_slot_pages(req.slot)
            self._journal_retire(req, "error", error=str(err))
            self.tracer.finish(rid, "error", error=str(err),
                               output_tokens=len(req.out_tokens))
            req.done.set()

    def shutdown_save(self, path: str) -> None:
        """Clean-shutdown checkpoint: save the live registry — UNLESS
        this process wrote a pre-fail snapshot and it still holds
        resumable records, in which case that file is the authoritative
        failure-time state (serving was over; saving the emptied
        registry would clobber it). Holds the same lock as _fail_all so
        a SIGTERM racing a heartbeat failure cannot read
        _prefail_written before the pre-fail write lands."""
        from cake_tpu.serve import checkpoint
        with self._ckpt_lock:
            if (getattr(self, "_prefail_written", False)
                    and checkpoint.has_resumable(path)):
                log.info("keeping pre-fail snapshot at %s", path)
                return
            checkpoint.write(checkpoint.snapshot(self), path)
            if self._journal is not None:
                # compaction handshake: the snapshot now owns every
                # journaled record — truncating keeps the two restart
                # sources disjoint (serve/journal.py)
                self._journal.truncate("checkpoint")

    def _snapshot_before_fail(self, requests=None) -> None:
        """Best-effort pre-fail checkpoint (no-op unless api.start armed
        `snapshot_path`). Caller must hold _ckpt_lock. Inline and
        device-free by construction: arming pairs with
        checkpoint.warm_fingerprint, so the fingerprint is memoized and
        the snapshot is pure Python plus one local write — safe even
        with the mesh wedged on a dead host. The guard below keeps it
        that way if the arming contract ever drifts.

        requests: records captured with checkpoint.snapshot_requests
        BEFORE the registry was emptied — the engine loop's fatal path
        fails its clients first (fast) and writes the snapshot after,
        from this capture. Sets `_prefail_written`, which the shutdown
        save consults to avoid clobbering this file (api/server.py
        save_and_exit)."""
        path = getattr(self, "snapshot_path", None)
        if not path:
            return
        if requests is None and not self._requests:
            # fatal declared after the registry was already emptied by
            # an engine-loop failure (the same event, seen twice): use
            # that failure's capture if it is fresh — requests from an
            # old, genuinely recovered error must not resurrect
            stash = getattr(self, "_fail_recs", None)
            # the window must cover the heartbeat stale interval (the
            # monitor is exactly the thread that arrives late) — cli
            # sets fail_recs_ttl from --heartbeat-timeout
            ttl = getattr(self, "fail_recs_ttl", 60.0)
            if stash is not None and time.monotonic() - stash[0] < ttl:
                requests = stash[1]
            else:
                return
        if getattr(self, "_ckpt_fingerprint", None) is None:
            log.warning("pre-fail snapshot skipped: fingerprint was not "
                        "warmed at arming time (would touch a possibly "
                        "wedged device)")
            return
        try:
            from cake_tpu.serve import checkpoint
            snap = checkpoint.snapshot(self, requests=requests)
            if not any(checkpoint.is_resumable(r)
                       for r in snap["requests"]):
                return   # nothing worth preserving
            checkpoint.write(snap, path)
            self._prefail_written = True
            if self._journal is not None:
                # same handshake as shutdown_save: the pre-fail
                # snapshot supersedes the journaled history
                self._journal.truncate("checkpoint")
            log.info("pre-fail snapshot saved to %s", path)
        except Exception:  # noqa: BLE001
            log.exception("pre-fail snapshot failed")


class QueueFullError(Exception):
    """Admission queue full. retry_after: computed seconds a client
    should wait before retrying — derived from the measured service
    rate when load shedding is on, else a 1s floor (the API surfaces
    it as HTTP 429 + Retry-After, api/server.py)."""

    def __init__(self, msg: str = "engine queue full",
                 retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = retry_after


@jax.jit
def _split_keys(keys):
    """Split a [B]-vector of PRNG keys into (next_keys, subkeys)."""
    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return split[:, 0], split[:, 1]


def _masked_sample(active_mask, keys, logits, ring, steps, temp, top_p,
                   penalty, *, top_k, n_top=0):
    """ONE per-row sample with masked state advance — the single source of
    the engine's sampling semantics: rows outside active_mask keep their
    PRNG key and ring untouched. Used eagerly by _sample_rows and traced
    inside _decode_scan, so the two decode paths cannot drift.
    Returns (next_tokens [B], keys, ring, logprobs [B],
    top ids [B, n_top], top logprobs [B, n_top])."""
    new_keys, sub = _split_keys(keys)
    nxt, lp, top_ids, top_lps = sample_tokens_ragged(
        sub, logits, ring, temp, top_p, penalty, top_k=top_k, n_top=n_top)
    keys = jnp.where(active_mask[:, None], new_keys, keys)
    ring = jnp.where(active_mask[:, None],
                     update_ring_per_row(ring, nxt, steps), ring)
    return nxt, keys, ring, lp, top_ids, top_lps


def make_decode_scan(forward_fn, out_sharding=None):
    """Build a jitted num_steps-ragged-decode+sample scan over any
    ragged forward (single-device model.forward_ragged, or the
    shard_mapped pipelined forward from parallel.pipeline
    .make_engine_step_fns — the step_fns-forces-scan-1 limitation is
    gone: a pipelined engine amortizes host dispatch across K tokens
    per round trip exactly like the single-device engine).

    forward_fn(params, tokens, cache, pos, active, rope, config)
    -> (logits, cache), with model.forward_ragged's signature.
    out_sharding: optional sharding constraint for the non-cache
    outputs (multi-host serving localizes them per process, so they
    must leave the program fully replicated).

    Same per-row semantics as the single-step path (_do_decode +
    _sample_rows — both go through _masked_sample): inactive rows touch
    neither their cache lines nor their PRNG/ring state, and a row that
    emits EOS mid-scan freezes for the remaining steps — in single-step
    mode the scheduler frees the slot immediately, so without freezing
    the slot's PRNG/ring stream would diverge between the two modes.
    A row also freezes once it has emitted `budget[row]` tokens within
    this call, so a scan may be dispatched past a request's
    max_new_tokens (or chained speculatively, _decode_burst) without
    writing a single token beyond the budget.
    Returns ([B, num_steps] tokens, [B, num_steps] logprobs,
    [B, num_steps, n_top] x2, cache, keys, ring, state) where state =
    (tok, pos, steps, live) is the final carry — feeding it back as
    (last_tok, pos, steps, active) chains a follow-up scan entirely on
    device (no host round-trip between scans). The host mirrors
    (_pos/_steps/_last_tok) are advanced by the caller.
    """

    @partial(jax.jit, static_argnames=("config", "num_steps", "top_k",
                                       "n_top"),
             donate_argnames=("cache", "keys", "ring"))
    def decode_scan(params, last_tok, pos, active, cache: KVCache, rope,
                    config, keys, ring, steps, temp, top_p, penalty,
                    budget, num_steps: int, top_k, n_top: int = 0):
        eos_ids = jnp.asarray(config.eos_token_ids, jnp.int32)
        steps_in = steps

        def body(carry, _):
            tok, pos, cache, keys, ring, steps, live = carry
            # per-row budget freeze: emitted-so-far = steps - steps_in
            # (both advance only while live), so a row stops producing
            # the moment its allowance for this call is used up
            live = live & ((steps - steps_in) < budget)
            logits, cache = forward_fn(params, tok[:, None], cache, pos,
                                       live, rope, config)
            nxt, keys, ring, lp, t_i, t_l = _masked_sample(
                live, keys, logits, ring, steps, temp, top_p, penalty,
                top_k=top_k, n_top=n_top)
            tok = jnp.where(live, nxt, tok)
            pos = pos + live
            steps = steps + live
            live = live & ~jnp.isin(nxt, eos_ids)
            return ((tok, pos, cache, keys, ring, steps, live),
                    (nxt, lp, t_i, t_l))

        ((tok, pos, cache, keys, ring, steps, live),
         (toks, lps, tops_i, tops_l)) = jax.lax.scan(
            body, (last_tok, pos, cache, keys, ring, steps, active), None,
            length=num_steps)
        # [B, num_steps(, n_top)] each
        outs = (toks.T, lps.T, jnp.swapaxes(tops_i, 0, 1),
                jnp.swapaxes(tops_l, 0, 1), keys, ring)
        if out_sharding is not None:
            outs = tuple(jax.lax.with_sharding_constraint(o, out_sharding)
                         for o in outs)
        toks_o, lps_o, ti_o, tl_o, keys_o, ring_o = outs
        state = (tok, pos, steps, live)
        return toks_o, lps_o, ti_o, tl_o, cache, keys_o, ring_o, state

    return decode_scan


def _builtin_forward_ragged(params, tokens, cache, pos, active, rope,
                            config):
    from cake_tpu.models.llama.model import forward_ragged
    return forward_ragged(params, tokens, cache, pos, active, rope, config)


_decode_scan = make_decode_scan(_builtin_forward_ragged)


def _ring_forward_ragged(params, tokens, cache, pos, active, rope, config):
    from cake_tpu.models.llama.model import forward_ragged_ring
    return forward_ragged_ring(params, tokens, cache, pos, active, rope,
                               config)


_decode_scan_ring = make_decode_scan(_ring_forward_ragged)


def _paged_forward_ragged(params, tokens, cache, pos, active, rope,
                          config):
    from cake_tpu.models.llama.paged import forward_ragged_paged
    return forward_ragged_paged(params, tokens, cache, pos, active, rope,
                                config)


# module-level like its dense/ring siblings so the jit cache is shared
# across engine instances (restart flows, test suites)
_decode_scan_paged = make_decode_scan(_paged_forward_ragged)


def _paged_forward_ragged_pallas(params, tokens, cache, pos, active,
                                 rope, config):
    from cake_tpu.models.llama.paged import forward_ragged_paged
    return forward_ragged_paged(params, tokens, cache, pos, active,
                                rope, config, attn="pallas")


_decode_scan_paged_pallas = make_decode_scan(_paged_forward_ragged_pallas)
