from cake_tpu.serve.engine import EngineStats, InferenceEngine, RequestHandle
from cake_tpu.serve.errors import (
    EngineRequestError, EngineResetError, PoisonRequestError,
    RecoveryConfig,
)

__all__ = [
    "InferenceEngine", "RequestHandle", "EngineStats",
    "EngineRequestError", "EngineResetError", "PoisonRequestError",
    "RecoveryConfig",
]
