from cake_tpu.serve.engine import EngineStats, InferenceEngine, RequestHandle

__all__ = ["InferenceEngine", "RequestHandle", "EngineStats"]
