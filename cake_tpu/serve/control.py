"""Multi-host serving control channel.

Under JAX multi-controller SPMD every process must dispatch the same
computation in the same order; an engine step launched only by the
coordinator would block forever in its first cross-process collective.
The reference solves the analogous problem with a worker RPC loop —
each worker blocks on the master's next message and executes it
(cake-core/src/cake/worker.rs:289-303). The TPU-native analog is this
control channel: the coordinator's engine publishes one tiny op record
(slot/token metadata, NOT tensors — hidden states move over ICI inside
the jitted program) before each device step, and every follower replays
the identical step so the SPMD dispatch lines up.

Transport: length-prefixed JSON over TCP. The payloads are ints/floats/
lists only — no pickle, so a hostile peer on the serving network cannot
execute code through this channel. The coordinator's bind address is
exchanged through a one-time `multihost_utils.broadcast_one_to_all`
(every process already shares the jax.distributed cluster), so no extra
address flag is needed beyond what `initialize()` already requires.
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from typing import Dict, List, Optional

from cake_tpu.obs import metrics as _m
from cake_tpu.utils import wire as _wire

log = logging.getLogger(__name__)

# the shared length-prefix framing (cake_tpu/utils/wire.py — ONE copy
# for the control and telemetry planes); module aliases kept so the
# rest of this file (and its tests) read unchanged
_LEN = _wire.LEN
MAX_OP_BYTES = 16 << 20  # sanity bound; a real op is < max_seq_len ints

# -- wire metrics ------------------------------------------------------------
# The control/heartbeat plane carries ALL cross-host coordination, yet
# until these it emitted nothing — a slow or flapping op stream was
# invisible. Both sides increment the same family names in their OWN
# process registry; follower-side samples reach the coordinator's
# /metrics with a host label via telemetry federation
# (obs/federation.py).
_CONTROL_OPS = _m.counter(
    "cake_control_ops_total",
    "Control-channel ops by op type (coordinator: published; follower: "
    "received/replayed — each side counts in its own process registry)",
    labelnames=("op",))
_CONTROL_BYTES = _m.counter(
    "cake_control_bytes_total",
    "Control-channel wire bytes incl. the length prefix, by direction "
    "(tx = coordinator publish fan-out across followers, rx = follower "
    "frame receive)",
    labelnames=("dir",))
_CONTROL_PUBLISH = _m.histogram(
    "cake_control_publish_seconds",
    "Wall seconds per ControlServer.publish (serialize + fan the op out "
    "to every follower socket) — the engine thread pays this before "
    "each replayed device step",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0))
_FOLLOWER_LAG = _m.gauge(
    "cake_control_follower_lag_ops",
    "Published-op seq minus the follower's last-applied seq (reported "
    "in its telemetry frames) — a growing lag means a follower is "
    "falling behind the SPMD dispatch stream",
    labelnames=("follower",))


class ControlDesyncError(RuntimeError):
    """A follower observed a GAP in the published op seq stream: one or
    more ops were never received, so its mirrored engine state has
    diverged from the coordinator's. Replaying further ops would
    silently desync the SPMD dispatch — the only safe move is to fail
    loudly and disconnect (the coordinator's next publish then raises
    instead of wedging a collective)."""


def broadcast_control_address(addr: Optional[str]) -> str:
    """Share the coordinator's control address with every process.

    The coordinator passes its "host:port"; followers pass None. Uses a
    fixed 128-byte buffer so the collective has one static shape. Must be
    called at the same program point on every process (it is a
    collective)."""
    import numpy as np
    from jax.experimental import multihost_utils

    # worst case: THREE 253-char DNS-name ":65535" fields (control,
    # heartbeat, telemetry collector — cli._serve_multihost ships four
    # |-separated fields) + the 32-hex token fits with room
    buf = np.zeros(1024, np.uint8)
    if addr:
        raw = addr.encode()
        if len(raw) > buf.size:
            raise ValueError(f"control address too long: {addr!r}")
        buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    return bytes(np.asarray(out)).rstrip(b"\0").decode()


_send_msg = _wire.send_msg


class ControlServer:
    """Coordinator side: accepts one connection per follower, then
    `publish()`es each op to all of them in dispatch order (TCP keeps
    per-follower ordering; every follower sees the same sequence).

    token: shared secret (distributed through the jax.distributed
    broadcast, which only cluster members receive). A connection that
    does not present it within 10s is dropped without ever occupying a
    follower slot or receiving an op — so a rogue peer on the serving
    network can neither exhaust the slots nor observe prompt token ids."""

    # cakelint guards discipline: every dotted use of the injector must
    # be `is not None`-guarded (disabled plane = one attribute test)
    OPTIONAL_PLANES = ("faults",)

    def __init__(self, n_followers: int, host: str = "",
                 port: int = 0, accept_timeout: float = 120.0,
                 token: Optional[str] = None):
        self.n_followers = n_followers
        self.token = token
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((host, port))
            self._sock.listen(max(n_followers, 1))
        except OSError:
            # callers retry with a different host on bind failure; the
            # half-constructed socket must not leak its fd
            self._sock.close()
            raise
        self._accept_timeout = accept_timeout
        self._conns: List[socket.socket] = []
        # parallel to _conns: per-follower wire bookkeeping — peer
        # address + the last op seq actually written to that socket.
        # With follower_acks (last-applied seqs reported back through
        # telemetry frames) a disconnect is diagnosable post-mortem:
        # the log line says exactly how far the dead follower got.
        self._peers: List[Dict] = []
        # follower name (telemetry host id) -> last-acked applied seq
        self.follower_acks: Dict[str, int] = {}
        self._seq = 0                # monotonic published-op counter
        self._lock = threading.Lock()
        # deterministic fault injection (cake_tpu/faults): the engine's
        # attach_control points this at its injector so a --fault-plan
        # can fail the op publish exactly like a dead follower would
        self.faults = None

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def accept_followers(self) -> None:
        import hmac
        import time as _time

        deadline = _time.monotonic() + self._accept_timeout
        while len(self._conns) < self.n_followers:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"only {len(self._conns)}/{self.n_followers} followers"
                    f" connected within {self._accept_timeout}s")
            self._sock.settimeout(remaining)
            try:
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            if self.token is not None:
                # bounded hello (cake_tpu/utils/wire.py): the length
                # is size-capped (a token is tens of bytes — an
                # attacker-controlled multi-GiB length must not
                # allocate) and the whole read wall-time-capped with
                # an ABSOLUTE deadline (per-recv timeouts would
                # multiply under byte-trickling and hold the accept
                # loop hostage)
                from cake_tpu.utils.wire import recv_bounded_msg
                hd = _time.monotonic() + min(
                    10.0, max(deadline - _time.monotonic(), 0.1))
                hello = recv_bounded_msg(conn, 256, hd)
                if hello is None or not hmac.compare_digest(
                        hello, self.token.encode()):
                    log.warning("control: rejected peer %s (bad token)",
                                peer)
                    conn.close()
                    continue
            conn.settimeout(None)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            self._peers.append({"peer": "%s:%s" % peer[:2],
                                "last_sent_seq": 0})
            log.info("control: follower connected from %s", peer)

    def publish(self, op: dict) -> None:
        """Send one op to every follower, stamped with a monotonically
        increasing ``seq``. Called from the engine thread immediately
        before it dispatches the corresponding device step. Followers
        verify the seq stream is gapless (ControlClient.recv raises
        ControlDesyncError on a gap) and report their last-applied seq
        back through telemetry frames (note_ack)."""
        if self.faults is not None:
            self.faults.check("control.publish")
        t0 = time.perf_counter()
        nbytes = 0
        with self._lock:
            self._seq += 1
            seq = self._seq
            payload = json.dumps({**op, "seq": seq}).encode()
            for conn, meta in zip(self._conns, self._peers):
                try:
                    _send_msg(conn, payload)
                except OSError:
                    # a dead follower cannot be skipped silently — the
                    # SPMD program it was part of will hang; surface it
                    # WITH the wire state (how far this follower got,
                    # and what every follower last acked) so the
                    # desync is diagnosable post-mortem
                    log.error(
                        "control: follower %s connection lost at "
                        "publish seq %d (last_sent_seq=%d, "
                        "follower_acks=%s)", meta["peer"], seq,
                        meta["last_sent_seq"], dict(self.follower_acks))
                    raise RuntimeError(
                        "control: follower connection lost; the SPMD "
                        f"mesh is no longer fully driven (follower "
                        f"{meta['peer']} last_sent_seq="
                        f"{meta['last_sent_seq']}, publishing seq "
                        f"{seq}, acks {dict(self.follower_acks)})")
                meta["last_sent_seq"] = seq
                nbytes += _LEN.size + len(payload)
        _CONTROL_OPS.labels(op=str(op.get("op", "?"))).inc()
        if nbytes:
            _CONTROL_BYTES.labels(dir="tx").inc(nbytes)
        _CONTROL_PUBLISH.observe(time.perf_counter() - t0)

    @property
    def published_seq(self) -> int:
        """Seq of the newest published op (0 = nothing published) —
        the minuend of every follower's lag."""
        with self._lock:
            return self._seq

    def note_ack(self, follower: str, applied_seq: int) -> None:
        """Record a follower's last-APPLIED op seq (reported in its
        telemetry frame, obs/federation.py) and refresh its lag gauge.
        Keyed by the follower's telemetry host id (proc1, ...)."""
        with self._lock:
            self.follower_acks[str(follower)] = int(applied_seq)
            lag = max(0, self._seq - int(applied_seq))
        _FOLLOWER_LAG.labels(follower=str(follower)).set(lag)

    def wire_state(self) -> Dict:
        """Control-plane wire introspection for recovery_state() /
        post-mortems: the published seq, each connection's last-sent
        seq, and the last-acked applied seqs by follower name."""
        with self._lock:
            return {
                "published_seq": self._seq,
                "followers": [dict(meta) for meta in self._peers],
                "acks": dict(self.follower_acks),
            }

    def wait_closed(self, timeout: float = 30.0) -> None:
        """Block until every follower closes its end (EOF). Called during
        coordinator teardown so the jax.distributed leader service stays
        alive until followers have disconnected from it — otherwise their
        coordination-service heartbeat aborts the follower process."""
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.settimeout(timeout)
                while conn.recv(4096):
                    pass  # followers send nothing; drain until EOF
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()
        self._sock.close()


class ControlClient:
    """Follower side: connect (with retries — the coordinator may still
    be binding), present the shared token, and iterate ops until the
    stream closes."""

    # cakelint guards discipline, same as ControlServer
    OPTIONAL_PLANES = ("faults",)

    def __init__(self, address: str, connect_timeout: float = 120.0,
                 token: Optional[str] = None):
        host, port = address.rsplit(":", 1)
        deadline = connect_timeout
        # last op seq seen on this channel: recv() enforces a gapless
        # stream (a GAP = missed ops = diverged mirror state) with a
        # typed ControlDesyncError instead of silently replaying on
        self._last_seq = 0
        t0 = time.monotonic()
        last: Optional[Exception] = None
        while time.monotonic() - t0 < deadline:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=10.0)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                if token is not None:
                    _send_msg(self._sock, token.encode())
                self._sock.settimeout(None)  # ops may be minutes apart
                return
            except OSError as e:
                last = e
                time.sleep(0.2)
        raise ConnectionError(
            f"could not reach control server at {address}: {last}")

    # follower-side fault injection point (cake_tpu/faults): cli wires
    # the follower's --fault-plan here so a chaos run can fail an op
    # receive exactly like a truncated stream would
    faults = None
    # partial-frame carry-over: bytes consumed before a recv() timeout
    # are KEPT here and resumed by the next call — the liveness retry
    # loop must never re-enter mid-frame and desync the op stream, and
    # a coordinator that dies WITHOUT a FIN mid-frame must still hit
    # the timeout (no unbounded blocking read anywhere)
    _rbuf = b""

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Next op, or None when the coordinator closed the channel.
        With a timeout, raises socket.timeout when the wait for more
        frame bytes exceeds it — whether the frame has started or not
        (a mid-frame peer death with no FIN must not hang the
        follower); partially-read bytes persist in _rbuf, so a retry
        resumes the SAME frame instead of desyncing the stream."""
        if self.faults is not None:
            self.faults.check("control.recv")

        def fill(n: int) -> bool:
            """Grow _rbuf to n bytes; False = clean close. Timeouts
            propagate with everything read so far preserved."""
            while len(self._rbuf) < n:
                part = self._sock.recv(n - len(self._rbuf))
                if not part:
                    return False
                self._rbuf += part
            return True

        self._sock.settimeout(timeout)
        try:
            if not fill(_LEN.size):
                return None
            (n,) = _LEN.unpack(self._rbuf[:_LEN.size])
            if n > MAX_OP_BYTES:
                raise ValueError(f"oversized control op: {n} bytes")
            if not fill(_LEN.size + n):
                return None
        finally:
            self._sock.settimeout(None)
        payload = self._rbuf[_LEN.size:]
        self._rbuf = b""
        op = json.loads(payload)
        _CONTROL_BYTES.labels(dir="rx").inc(_LEN.size + len(payload))
        seq = op.get("seq") if isinstance(op, dict) else None
        if isinstance(seq, int):
            if self._last_seq and seq != self._last_seq + 1:
                raise ControlDesyncError(
                    f"control op seq gap: expected "
                    f"{self._last_seq + 1}, got {seq} — this follower "
                    f"missed {seq - self._last_seq - 1} op(s); its "
                    "mirrored state has diverged and replaying further "
                    "ops would silently desync the SPMD dispatch")
            self._last_seq = seq
        if isinstance(op, dict):
            _CONTROL_OPS.labels(op=str(op.get("op", "?"))).inc()
        return op

    def close(self) -> None:
        self._sock.close()
