"""Multi-host serving control channel.

Under JAX multi-controller SPMD every process must dispatch the same
computation in the same order; an engine step launched only by the
coordinator would block forever in its first cross-process collective.
The reference solves the analogous problem with a worker RPC loop —
each worker blocks on the master's next message and executes it
(cake-core/src/cake/worker.rs:289-303). The TPU-native analog is this
control channel: the coordinator's engine publishes one tiny op record
(slot/token metadata, NOT tensors — hidden states move over ICI inside
the jitted program) before each device step, and every follower replays
the identical step so the SPMD dispatch lines up.

Transport: length-prefixed JSON over TCP. The payloads are ints/floats/
lists only — no pickle, so a hostile peer on the serving network cannot
execute code through this channel. The coordinator's bind address is
exchanged through a one-time `multihost_utils.broadcast_one_to_all`
(every process already shares the jax.distributed cluster), so no extra
address flag is needed beyond what `initialize()` already requires.
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
from typing import List, Optional

log = logging.getLogger(__name__)

_LEN = struct.Struct("!I")
MAX_OP_BYTES = 16 << 20  # sanity bound; a real op is < max_seq_len ints


def broadcast_control_address(addr: Optional[str]) -> str:
    """Share the coordinator's control address with every process.

    The coordinator passes its "host:port"; followers pass None. Uses a
    fixed 128-byte buffer so the collective has one static shape. Must be
    called at the same program point on every process (it is a
    collective)."""
    import numpy as np
    from jax.experimental import multihost_utils

    # 253-char max DNS name + ":65535|" + 32-hex token fits with room
    buf = np.zeros(320, np.uint8)
    if addr:
        raw = addr.encode()
        if len(raw) > buf.size:
            raise ValueError(f"control address too long: {addr!r}")
        buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    return bytes(np.asarray(out)).rstrip(b"\0").decode()


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


class ControlServer:
    """Coordinator side: accepts one connection per follower, then
    `publish()`es each op to all of them in dispatch order (TCP keeps
    per-follower ordering; every follower sees the same sequence).

    token: shared secret (distributed through the jax.distributed
    broadcast, which only cluster members receive). A connection that
    does not present it within 10s is dropped without ever occupying a
    follower slot or receiving an op — so a rogue peer on the serving
    network can neither exhaust the slots nor observe prompt token ids."""

    def __init__(self, n_followers: int, host: str = "",
                 port: int = 0, accept_timeout: float = 120.0,
                 token: Optional[str] = None):
        self.n_followers = n_followers
        self.token = token
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((host, port))
            self._sock.listen(max(n_followers, 1))
        except OSError:
            # callers retry with a different host on bind failure; the
            # half-constructed socket must not leak its fd
            self._sock.close()
            raise
        self._accept_timeout = accept_timeout
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        # deterministic fault injection (cake_tpu/faults): the engine's
        # attach_control points this at its injector so a --fault-plan
        # can fail the op publish exactly like a dead follower would
        self.faults = None

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def accept_followers(self) -> None:
        import hmac
        import time as _time

        deadline = _time.monotonic() + self._accept_timeout
        while len(self._conns) < self.n_followers:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"only {len(self._conns)}/{self.n_followers} followers"
                    f" connected within {self._accept_timeout}s")
            self._sock.settimeout(remaining)
            try:
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            if self.token is not None:
                # bound BOTH the hello length (a token is tens of bytes —
                # an attacker-controlled multi-GiB length must not
                # allocate) and its wall time with an ABSOLUTE deadline
                # (per-recv timeouts would multiply under byte-trickling
                # and hold the accept loop hostage)
                hd = _time.monotonic() + min(
                    10.0, max(deadline - _time.monotonic(), 0.1))

                def recv_bounded(n: int) -> Optional[bytes]:
                    data = b""
                    while len(data) < n:
                        rem = hd - _time.monotonic()
                        if rem <= 0:
                            return None
                        conn.settimeout(rem)
                        part = conn.recv(n - len(data))
                        if not part:
                            return None
                        data += part
                    return data

                try:
                    head = recv_bounded(_LEN.size)
                    n = _LEN.unpack(head)[0] if head else 0
                    hello = (recv_bounded(n)
                             if head and 0 < n <= 256 else None)
                except OSError:
                    hello = None
                if hello is None or not hmac.compare_digest(
                        hello, self.token.encode()):
                    log.warning("control: rejected peer %s (bad token)",
                                peer)
                    conn.close()
                    continue
            conn.settimeout(None)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            log.info("control: follower connected from %s", peer)

    def publish(self, op: dict) -> None:
        """Send one op to every follower. Called from the engine thread
        immediately before it dispatches the corresponding device step."""
        if self.faults is not None:
            self.faults.check("control.publish")
        payload = json.dumps(op).encode()
        with self._lock:
            for conn in self._conns:
                try:
                    _send_msg(conn, payload)
                except OSError:
                    # a dead follower cannot be skipped silently — the
                    # SPMD program it was part of will hang; surface it
                    raise RuntimeError(
                        "control: follower connection lost; the SPMD "
                        "mesh is no longer fully driven")

    def wait_closed(self, timeout: float = 30.0) -> None:
        """Block until every follower closes its end (EOF). Called during
        coordinator teardown so the jax.distributed leader service stays
        alive until followers have disconnected from it — otherwise their
        coordination-service heartbeat aborts the follower process."""
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.settimeout(timeout)
                while conn.recv(4096):
                    pass  # followers send nothing; drain until EOF
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()
        self._sock.close()


class ControlClient:
    """Follower side: connect (with retries — the coordinator may still
    be binding), present the shared token, and iterate ops until the
    stream closes."""

    def __init__(self, address: str, connect_timeout: float = 120.0,
                 token: Optional[str] = None):
        host, port = address.rsplit(":", 1)
        deadline = connect_timeout
        import time
        t0 = time.monotonic()
        last: Optional[Exception] = None
        while time.monotonic() - t0 < deadline:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=10.0)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                if token is not None:
                    _send_msg(self._sock, token.encode())
                self._sock.settimeout(None)  # ops may be minutes apart
                return
            except OSError as e:
                last = e
                time.sleep(0.2)
        raise ConnectionError(
            f"could not reach control server at {address}: {last}")

    # follower-side fault injection point (cake_tpu/faults): cli wires
    # the follower's --fault-plan here so a chaos run can fail an op
    # receive exactly like a truncated stream would
    faults = None
    # partial-frame carry-over: bytes consumed before a recv() timeout
    # are KEPT here and resumed by the next call — the liveness retry
    # loop must never re-enter mid-frame and desync the op stream, and
    # a coordinator that dies WITHOUT a FIN mid-frame must still hit
    # the timeout (no unbounded blocking read anywhere)
    _rbuf = b""

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Next op, or None when the coordinator closed the channel.
        With a timeout, raises socket.timeout when the wait for more
        frame bytes exceeds it — whether the frame has started or not
        (a mid-frame peer death with no FIN must not hang the
        follower); partially-read bytes persist in _rbuf, so a retry
        resumes the SAME frame instead of desyncing the stream."""
        if self.faults is not None:
            self.faults.check("control.recv")

        def fill(n: int) -> bool:
            """Grow _rbuf to n bytes; False = clean close. Timeouts
            propagate with everything read so far preserved."""
            while len(self._rbuf) < n:
                part = self._sock.recv(n - len(self._rbuf))
                if not part:
                    return False
                self._rbuf += part
            return True

        self._sock.settimeout(timeout)
        try:
            if not fill(_LEN.size):
                return None
            (n,) = _LEN.unpack(self._rbuf[:_LEN.size])
            if n > MAX_OP_BYTES:
                raise ValueError(f"oversized control op: {n} bytes")
            if not fill(_LEN.size + n):
                return None
        finally:
            self._sock.settimeout(None)
        payload = self._rbuf[_LEN.size:]
        self._rbuf = b""
        return json.loads(payload)

    def close(self) -> None:
        self._sock.close()
