"""Typed request-failure errors + recovery tuning for the engine.

Before this module, a request killed by an engine failure carried
whatever raw exception happened to escape the step — the API could only
map everything to a generic 500. Now every request failed by
``_fail_all`` or the quarantine path carries an ``EngineRequestError``
with an explicit ``retryable`` flag: the API maps retryable failures
(transient engine resets, storm-breaker stops) to 503 + an honest
computed Retry-After, and non-retryable ones (a poison request that
kept crashing the step it was in) to a terminal client error.
"""

from __future__ import annotations

from dataclasses import dataclass


class EngineRequestError(RuntimeError):
    """Base class for engine-originated request failures.

    retryable: True when the SAME request can reasonably be resubmitted
    (the failure was the engine's state, not the request); the API
    surfaces it as 503 + Retry-After instead of a 500."""

    retryable = False

    def __init__(self, msg: str, *, retryable=None):
        super().__init__(msg)
        if retryable is not None:
            self.retryable = bool(retryable)


class EngineResetError(EngineRequestError):
    """The engine failed and reset (or stopped) out from under this
    request — transient from the client's point of view: retry."""

    retryable = True


class PoisonRequestError(EngineRequestError):
    """This request was implicated in `implication_budget` consecutive
    failed steps and quarantined so the rest of the batch could
    recover. NOT retryable: resubmitting the same request would crash
    the engine again."""

    retryable = False

    def __init__(self, rid: int, crashes: int, cause: str):
        super().__init__(
            f"request {rid} quarantined after being implicated in "
            f"{crashes} consecutive failed engine steps (poison "
            f"request; last failure: {cause})")
        self.rid = rid
        self.crashes = crashes


class SwitchInFlightError(RuntimeError):
    """A live config switch (engine.reconfigure / cake_tpu/autotune)
    is already in flight; the API maps this to HTTP 409 on
    POST /api/v1/autotune. Retry after the current switch lands."""


class DrainingError(Exception):
    """Admission refused because the server is draining (POST
    /api/v1/drain or a SIGTERM in flight). NOT an EngineRequestError —
    the request was never admitted; the API maps it to HTTP 429 with
    the computed seconds until the drain completes as Retry-After (by
    then this process is gone and a balancer should have moved on,
    but an honest number beats a constant)."""

    def __init__(self, retry_after: float = 1.0):
        super().__init__("server draining: admissions are closed")
        self.retry_after = retry_after


def as_engine_error(err: Exception) -> EngineRequestError:
    """Wrap an arbitrary step failure in the typed, retryable-flagged
    form clients see — idempotent for already-typed errors."""
    if isinstance(err, EngineRequestError):
        return err
    return EngineResetError(
        f"engine failure: {type(err).__name__}: {err}")


@dataclass(frozen=True)
class RecoveryConfig:
    """Knobs for the crash-recovery loop (serve/engine._attempt_recovery).

    implication_budget: a request implicated in this many CONSECUTIVE
      failed steps is quarantined as poison (2 = one retry: the first
      failure could be anyone's; a second with the same request in the
      blast radius is on it).
    backoff_base_s/backoff_cap_s: exponential backoff between
      consecutive resets (first reset is immediate; the k-th waits
      min(base * 2^(k-2), cap)) so a persistent fault cannot spin the
      engine thread through reset storms at full speed.
    storm_resets/storm_window_s: the reset-storm breaker — this many
      resets inside the window means the fault is not transient:
      snapshot in-flight requests and stop cleanly (the pre-recovery
      behavior) instead of burning the pool forever.
    """

    implication_budget: int = 2
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 10.0
    storm_resets: int = 5
    storm_window_s: float = 60.0
