"""Write-ahead request journal: durable serving across hard process death.

PR 8 made the engine survive *step* failures, and clean stops snapshot
in-flight work (serve/checkpoint.py) — but a SIGKILL / OOM-kill / power
loss between snapshots still lost every in-flight stream. This module
closes that gap with a WAL (``--journal PATH``) on the shared
obs/jsonl.py appender:

  * one ``admit`` record per admission (rid, prompt ids, sampling
    params, priority class, idempotency key, config epoch);
  * one ``emit`` record per emitted-token batch (rid, token ids,
    cumulative count) — batched per engine iteration, so the journal
    costs one line per (request, iteration), not per token;
  * ``retire`` tombstones (retired / error / cancelled);
  * periodic in-place compaction (admit+emit consolidated per live
    request, tombstoned requests dropped) once the file exceeds
    ``compact_bytes``, plus the checkpoint handshake: every
    ``checkpoint.write`` of this engine's state truncates the journal
    (the snapshot now owns everything pre-write), so journal records
    are always strictly post-snapshot and the two sources never
    double-count a request.

On startup, ``recover(engine, ...)`` = ``checkpoint.restore`` + journal
replay: the merged state resubmits every non-retired request through
the existing fold-tokens-into-prompt path (checkpoint.resume), with
seniority class, preempt budget, penalty ring and idempotency key
preserved — greedy streams complete token-identical at f32 KV across a
``kill -9`` (the ``--fault-plan`` ``abort`` error kind stages one
deterministically).

Durability modes (``--journal-fsync``):

  * ``never``  — flush per line (OS buffer); a machine death can lose
    recent records, a process death cannot.
  * ``batch``  — fsync once per engine-iteration flush (default): at
    most one iteration's tokens are lost to power loss.
  * ``always`` — fsync after every append: admissions and token
    batches are durable before the engine proceeds. Slowest; for
    when a lost admission is unacceptable.

Replay is crash-safe itself: the journal is renamed to
``<path>.replaying`` before resubmission (each resubmitted request is
re-journaled into a fresh file as it lands), and a startup that finds a
leftover ``.replaying`` file replays from IT, discarding the partial
re-seed — a crash mid-recovery never loses a request.

Chaos: the ``journal.append`` / ``journal.fsync`` / ``journal.replay``
fault sites thread through here with the PR 8 ``is not None``
discipline, and the ``abort`` error kind (``os._exit``) is the in-tree
way to stage the crash drills this module exists to survive.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from cake_tpu.obs import metrics as _m
from cake_tpu.obs.jsonl import JsonlAppender

log = logging.getLogger(__name__)

FSYNC_MODES = ("never", "batch", "always")

# journal format version (the "start" header record carries it); bump
# on any record-grammar change so an old journal fails loudly instead
# of replaying misparsed state
JOURNAL_VERSION = 1

# default compaction threshold: once this many bytes have been
# appended since the last compaction/truncation, the engine-thread
# maybe_compact() rewrites the file in place (live requests only)
DEFAULT_COMPACT_BYTES = 16 * 1024 * 1024

_APPENDS = _m.counter(
    "cake_journal_appends_total",
    "Write-ahead request-journal records appended, by record type "
    "(serve/journal.py; admit / emit / retire / start)",
    labelnames=("rec",))
_BYTES = _m.counter(
    "cake_journal_bytes_total",
    "Bytes appended to the write-ahead request journal (--journal; "
    "resets never — compaction rewrites the file but the counter "
    "keeps accumulating)")
_FSYNC_SECONDS = _m.histogram(
    "cake_journal_fsync_seconds",
    "Latency of journal fsync barriers (--journal-fsync batch/always)",
    buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5, 1.0))
_REPLAYED = _m.counter(
    "cake_journal_replayed_requests_total",
    "Requests reconstructed from the journal (+ checkpoint base) and "
    "resubmitted into a restarted engine (serve/journal.recover)")
_DROPPED = _m.counter(
    "cake_journal_dropped_requests_total",
    "Journal-reconstructed requests that could not be resubmitted at "
    "replay (queue full, shrunk limits, malformed record)")
_REPLAY_SECONDS = _m.histogram(
    "cake_journal_replay_seconds",
    "Wall seconds for a startup journal replay (read + reconstruct + "
    "resubmit)",
    buckets=(.01, .05, .1, .5, 1.0, 5.0, 15.0, 60.0))
_COMPACTIONS = _m.counter(
    "cake_journal_compactions_total",
    "In-place journal compactions (size-triggered rewrite) plus "
    "checkpoint-handshake truncations",
    labelnames=("reason",))


class RequestJournal:
    """The engine-side WAL. Thread-safe: admissions journal from HTTP
    handler threads (under the engine's admission lock), emits/retires
    from the engine thread; one internal lock serializes the file.

    Fail-open like every obs sink: a real OSError (full disk, revoked
    path) disables the underlying appender with ONE warning — serving
    never trades a token emit for a journaling exception — and
    ``state()`` reports ``failed`` so /api/v1/health shows the journal
    went dark. Injected faults (--fault-plan journal.* sites) raise
    through instead: chaos exercises the failure path deliberately.
    """

    # cakelint guards discipline: the chaos plane is optional
    # (attached by the engine after construction; None without a
    # --fault-plan) — every dotted use needs `is not None`
    OPTIONAL_PLANES = ("faults",)

    def __init__(self, path: str, fsync: str = "batch",
                 compact_bytes: int = DEFAULT_COMPACT_BYTES):
        if fsync not in FSYNC_MODES:
            raise ValueError(
                f"--journal-fsync must be one of {', '.join(FSYNC_MODES)},"
                f" got {fsync!r}")
        self.path = path
        self.fsync = fsync
        self.compact_bytes = compact_bytes
        self._lock = threading.RLock()
        self._appender = JsonlAppender(path)
        self._header_written = False
        # engine attaches these after construction: the chaos plane
        # (faults) and the fingerprint source (owner — used for the
        # header so replay can refuse a different model's weights)
        self.faults = None
        self.owner = None
        # rid -> (token ids since last flush, absolute cumulative count)
        self._pending: Dict[int, Tuple[List[int], int]] = {}
        self._dirty = False           # appended since last fsync
        self._bytes_since_compact = 0
        # a replay_done marker is live in the current file (recover
        # consumed a sideline): compaction must preserve it, or a
        # failed sideline removal could mis-truncate the next startup
        self._replay_done = False
        self.appends = 0
        self.bytes_written = 0
        self.compactions = 0
        self.last_replay: Optional[Dict] = None

    # -- record writers ---------------------------------------------------

    def _fingerprint(self) -> Optional[Dict]:
        if self.owner is None:
            return None
        try:
            from cake_tpu.serve.checkpoint import _fingerprint
            return _fingerprint(self.owner)
        except Exception:  # noqa: BLE001 — header metadata must never
            # fail an append (e.g. a wedged device before warm)
            log.debug("journal: fingerprint unavailable", exc_info=True)
            return None

    def _append(self, obj: Dict) -> None:
        """One physical record append (caller holds the lock). Writes
        the generation header first on a fresh/truncated file."""
        if self.faults is not None:
            self.faults.check("journal.append")
        if not self._header_written:
            # set before the recursive call (it re-checks the flag),
            # but roll back if the header append itself fails — a
            # later append must retry the header, or the journal would
            # be permanently headerless (no version/fingerprint guard)
            self._header_written = True
            try:
                self._append({"rec": "start", "v": JOURNAL_VERSION,
                              "t": time.time(),
                              "fp": self._fingerprint()})
            except Exception:
                self._header_written = False
                raise
        line_len = self._appender.append(obj)
        if line_len:
            self.appends += 1
            self.bytes_written += line_len
            self._bytes_since_compact += line_len
            self._dirty = True
            _APPENDS.labels(rec=obj.get("rec", "?")).inc()
            _BYTES.inc(line_len)
            if self.fsync == "always":
                self._sync()

    def _sync(self) -> None:
        if not self._dirty:
            return
        if self.faults is not None:
            self.faults.check("journal.fsync")
        t0 = time.perf_counter()
        self._appender.sync()
        _FSYNC_SECONDS.observe(time.perf_counter() - t0)
        self._dirty = False

    @staticmethod
    def _request_records(req, epoch: int,
                         include_out: bool = False) -> tuple:
        """THE (admit, emit) record pair for one request, in ORIGINAL
        stream coordinates — shared by note_admit and the compactor so
        the two producers cannot drift. A replay-resubmitted request
        (req.replayed_tokens set) gets its fold suffix stripped back
        out of the prompt/prime and re-recorded as an emit, so a
        second crash replays the same stream and SSE event ids stay
        monotonic across any number of restarts. include_out
        additionally folds the current generation into the emit (the
        compactor's whole-state form). emit is None when there is
        nothing generated."""
        replayed = list(getattr(req, "replayed_tokens", ()) or ())
        ids = list(req.prompt_ids)
        if replayed:
            if ids[-len(replayed):] == replayed:
                ids = ids[:-len(replayed)]
            else:  # fold drifted (should not happen) — keep the fold
                replayed = []
        prime = list(req.prime_tokens or ())
        if replayed and prime[-len(replayed):] == replayed:
            # the resume fold primes the penalty ring with the
            # generated history; the emit record re-carries it, so
            # strip the overlap from the stored prime
            prime = prime[:-len(replayed)]
        admit = {"rec": "admit", "rid": req.rid, "t": time.time(),
                 "ids": ids,
                 "max_new": int(req.max_new_tokens) + len(replayed),
                 "temp": req.temperature, "top_p": req.top_p,
                 "pen": req.repeat_penalty, "prime": prime,
                 "prio": req.priority,
                 "key": getattr(req, "idempotency_key", None),
                 "epoch": epoch}
        out = replayed + (list(req.out_tokens) if include_out else [])
        emit = ({"rec": "emit", "rid": req.rid, "toks": out,
                 "n": len(out)} if out else None)
        return admit, emit

    def note_admit(self, req, config_epoch: int = 0) -> None:
        """Journal one admission (engine.submit, inside the admission
        lock, BEFORE the request is registered — the write-ahead
        invariant)."""
        with self._lock:
            admit, emit = self._request_records(req, config_epoch)
            self._append(admit)
            if emit is not None:
                self._append(emit)

    def note_emit(self, rid: int, token_id: int, n_abs: int) -> None:
        """Buffer one emitted token (engine thread). n_abs: the
        request's absolute generated count INCLUDING replayed tokens
        from previous process generations — the same coordinate SSE
        ``id:`` fields use."""
        with self._lock:
            toks, _ = self._pending.get(rid, ([], 0))
            toks.append(int(token_id))
            self._pending[rid] = (toks, int(n_abs))

    def _flush_rid(self, rid: int) -> None:
        ent = self._pending.pop(rid, None)
        if ent is not None and ent[0]:
            self._append({"rec": "emit", "rid": rid, "toks": ent[0],
                          "n": ent[1]})

    def flush(self) -> None:
        """Write one emit record per request touched since the last
        flush (end of each engine iteration), then the batch-mode
        fsync barrier."""
        with self._lock:
            rids = list(self._pending)
            for rid in rids:
                self._flush_rid(rid)
            if self.fsync == "batch":
                self._sync()

    def note_retire(self, rid: int, status: str,
                    error: Optional[str] = None) -> None:
        """Tombstone one request (retired / error / cancelled). Flushes
        the rid's buffered emits first so the tombstone is last."""
        with self._lock:
            self._flush_rid(rid)
            rec: Dict = {"rec": "retire", "rid": rid, "status": status}
            if error:
                rec["error"] = error
            self._append(rec)
            if self.fsync == "batch":
                self._sync()

    # -- compaction -------------------------------------------------------

    def truncate(self, reason: str = "checkpoint") -> None:
        """The checkpoint handshake: a just-written snapshot owns every
        record up to now, so the journal restarts empty — keeping the
        two sources disjoint by construction."""
        with self._lock:
            self._pending.clear()
            self._appender.close()
            try:
                open(self.path, "w").close()
            except OSError:
                log.warning("journal: truncate failed for %s", self.path,
                            exc_info=True)
            if reason == "checkpoint":
                # the snapshot supersedes ANY leftover replay sideline
                # too (one whose removal failed at recover time): drop
                # it so the next startup cannot merge stale state
                try:
                    os.remove(self.path + ".replaying")
                except OSError:
                    pass
            self._appender = JsonlAppender(self.path)
            self._header_written = False
            self._dirty = False
            self._bytes_since_compact = 0
            self._replay_done = False
            self.compactions += 1
            _COMPACTIONS.labels(reason=reason).inc()

    def maybe_compact(self, engine) -> None:
        """Size-triggered in-place compaction (engine thread, between
        iterations — the request registry is stable there): rewrite
        the journal as one admit+emit pair per LIVE request, dropping
        tombstoned history. Atomic (tmp + rename); on any failure the
        original file stays authoritative."""
        with self._lock:
            if self._bytes_since_compact < self.compact_bytes:
                return
            tmp = f"{self.path}.{os.getpid()}.compact.tmp"
            try:
                with open(tmp, "w") as f:
                    f.write(json.dumps(
                        {"rec": "start", "v": JOURNAL_VERSION,
                         "t": time.time(),
                         "fp": self._fingerprint()}) + "\n")
                    if self._replay_done:
                        f.write(json.dumps({"rec": "replay_done",
                                            "t": time.time()}) + "\n")
                    for _rid, req in sorted(dict(engine._requests).items()):
                        if req.done.is_set():
                            continue
                        admit, emit = self._request_records(
                            req, getattr(engine, "config_epoch", 0),
                            include_out=True)
                        f.write(json.dumps(admit) + "\n")
                        if emit is not None:
                            f.write(json.dumps(emit) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                log.warning("journal: compaction write failed; keeping "
                            "the uncompacted journal", exc_info=True)
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                # back off: do not retry every iteration on a full disk
                self._bytes_since_compact = 0
                return
            self._appender.close()
            os.replace(tmp, self.path)
            self._appender = JsonlAppender(self.path)
            self._header_written = True   # the tmp wrote the header
            self._dirty = False
            self._bytes_since_compact = 0
            self.compactions += 1
            _COMPACTIONS.labels(reason="size").inc()
            log.info("journal: compacted %s (%d live request(s))",
                     self.path, len(engine._requests))

    # -- lifecycle / introspection ---------------------------------------

    def state(self) -> Dict:
        """Health-endpoint view (/api/v1/health "journal" block)."""
        with self._lock:
            out = {
                "path": self.path,
                "fsync": self.fsync,
                "appends": self.appends,
                "bytes_written": self.bytes_written,
                "buffered_rids": len(self._pending),
                "compactions": self.compactions,
                "failed": self._appender.failed,
            }
            if self.last_replay is not None:
                out["last_replay"] = dict(self.last_replay)
            return out

    def close(self) -> None:
        with self._lock:
            for rid in list(self._pending):
                self._flush_rid(rid)
            self._appender.close()


# -- reading / replay ------------------------------------------------------


def read_records(path: str) -> Tuple[List[Dict], int, bool]:
    """Tolerant journal read: returns (records, bad_lines, torn_tail).
    A torn FINAL line is the expected signature of a killed writer
    (tolerated, like obs/jsonl.read_jsonl); bad lines elsewhere are
    mid-file corruption the caller may want to report. A missing file
    reads as empty."""
    records: List[Dict] = []
    bad = 0
    last_bad = False
    try:
        fh = open(path, "r", errors="replace")
    except OSError:
        return records, 0, False
    with fh:
        for line in fh:
            stripped = line.strip()
            if not stripped:
                # any following line — even a blank — proves the bad
                # line was newline-terminated: complete-but-corrupt,
                # not a torn tail
                last_bad = False
                continue
            try:
                rec = json.loads(stripped)
                if not isinstance(rec, dict):
                    raise ValueError("not an object")
                records.append(rec)
                last_bad = False
            except (json.JSONDecodeError, ValueError):
                bad += 1
                last_bad = True
    torn_tail = last_bad
    if torn_tail:
        bad -= 1   # the torn tail is reported separately, not as corruption
    return records, bad, torn_tail


def replay_state(records: List[Dict],
                 base: Optional[List[Dict]] = None
                 ) -> Tuple[List[Dict], List[str], Optional[Dict]]:
    """Pure reconstruction: fold journal `records` over an optional
    checkpoint `base` (snapshot request records) into checkpoint-style
    request records, newest state last. Returns (records, findings,
    header) — findings are human-readable inconsistencies (orphaned
    emits, cumulative-count gaps, duplicate admits, emits after
    retire); replay proceeds best-effort past them, journal_check
    turns them into its rc=1 contract."""
    state: Dict[int, Dict] = {}
    findings: List[str] = []
    header: Optional[Dict] = None
    for rec in base or ():
        s = dict(rec)
        s.setdefault("replayed", [])
        s.setdefault("out_tokens", [])
        s["_base_out"] = len(s["out_tokens"])
        s["_base_remaining"] = s.get("remaining", 0)
        state[s["rid"]] = s
    for r in records:
        kind = r.get("rec")
        if kind == "start":
            if header is None:
                header = r
            continue
        if kind == "replay_done":
            # the consumed-sideline marker (recover): carries no
            # request state
            continue
        rid = r.get("rid")
        if not isinstance(rid, int):
            findings.append(f"{kind or '?'} record without a rid")
            continue
        if kind == "admit":
            if rid in state:
                findings.append(f"rid {rid}: duplicate admit")
            state[rid] = {
                "rid": rid,
                "prompt_ids": list(r.get("ids") or ()),
                "out_tokens": [],
                "replayed": [],
                "max_new": int(r.get("max_new") or 0),
                "temperature": r.get("temp", 0.0),
                "top_p": r.get("top_p", 1.0),
                "repeat_penalty": r.get("pen", 1.0),
                "prime": list(r.get("prime") or ()),
                "priority": r.get("prio", "standard"),
                "idempotency_key": r.get("key"),
                "finished": False,
                "error": None,
                "emits": 0,
            }
        elif kind == "emit":
            s = state.get(rid)
            if s is None:
                findings.append(f"rid {rid}: orphaned emit (no admit, "
                                "no checkpoint record)")
                continue
            if s.get("finished"):
                findings.append(f"rid {rid}: emit after retire")
            toks = list(r.get("toks") or ())
            out = s["out_tokens"]
            offset = len(s.get("replayed") or ())
            n = r.get("n")
            s["emits"] = s.get("emits", 0) + 1
            if isinstance(n, int):
                rel = n - len(toks) - offset
                if rel < 0 or rel > len(out):
                    findings.append(
                        f"rid {rid}: emit cumulative count {n} does not "
                        f"extend the {offset + len(out)} tokens on "
                        "record (gap or overlap)")
                    out.extend(toks)
                else:
                    del out[rel:]
                    out.extend(toks)
            else:
                out.extend(toks)
        elif kind == "retire":
            s = state.get(rid)
            if s is None:
                findings.append(f"rid {rid}: retire without admit")
                continue
            s["finished"] = True
            s["status"] = r.get("status", "retired")
            if r.get("status") == "error":
                s["error"] = r.get("error") or "error"
        else:
            findings.append(f"unknown record type {kind!r}")
    out_recs: List[Dict] = []
    for rid in sorted(state):
        s = state[rid]
        new_out = len(s["out_tokens"]) - s.pop("_base_out", 0)
        if "_base_remaining" in s:
            s["remaining"] = max(0, s.pop("_base_remaining") - new_out)
        else:
            s["remaining"] = max(0, s.get("max_new", 0)
                                 - len(s["out_tokens"]))
        # penalty ring history: prime + every generated token (base
        # records already fold their pre-snapshot history into
        # penalty_context; journal admits carry prime explicitly)
        if s.get("penalty_context") is not None:
            pc = list(s["penalty_context"]) + s["out_tokens"][
                len(s["out_tokens"]) - new_out:]
        else:
            pc = list(s.get("prime", ())) + list(s.get("replayed", ())) \
                + list(s["out_tokens"])
        s["penalty_context"] = pc
        out_recs.append(s)
    return out_recs, findings, header


def recover(engine, checkpoint_path: Optional[str] = None,
            strict: bool = True) -> Tuple[List, List[Dict]]:
    """Cold-restart recovery: checkpoint.restore + journal replay.

    Reads the engine's armed journal (plus the checkpoint base when
    `checkpoint_path` names one), reconstructs every non-retired
    request, sidelines the journal to ``<path>.replaying``, resubmits
    the survivors through checkpoint.resume (fold-tokens-into-prompt;
    seniority class / preempt budget / penalty ring / idempotency key
    preserved; each resubmission re-journals itself into the fresh
    file), seeds retired-but-keyed records into the engine's
    idempotency registry, then removes the sideline. Crash-safe: a
    death mid-recovery leaves ``.replaying`` behind, and the next
    startup replays from it, discarding the partial re-seed.

    Returns (handles, finished_records) like checkpoint.restore.
    """
    from cake_tpu.serve import checkpoint

    j = getattr(engine, "_journal", None)
    if j is None:
        if checkpoint_path and os.path.exists(checkpoint_path):
            return checkpoint.restore(engine, checkpoint_path,
                                      strict=strict)
        return [], []
    if j.faults is not None:
        j.faults.check("journal.replay")
    t0 = time.perf_counter()
    replay_path = j.path + ".replaying"
    if os.path.exists(replay_path):
        # a leftover sideline is EITHER a recovery that died
        # mid-resubmit (the sideline is the authority; the journal
        # holds only its partial re-seed) OR a consumed one whose
        # removal failed (the journal — which then carries the
        # replay_done marker — is the authority, and truncating it
        # would destroy every post-recovery record)
        consumed = any(r.get("rec") == "replay_done"
                       for r in read_records(j.path)[0])
        if consumed:
            log.warning("journal: stale consumed sideline %s (its "
                        "removal failed last time); discarding it",
                        replay_path)
            try:
                os.remove(replay_path)
            except OSError:
                pass   # os.replace below overwrites it anyway
            if os.path.exists(j.path) and os.path.getsize(j.path) > 0:
                os.replace(j.path, replay_path)
        else:
            log.warning("journal: found %s — a previous replay was "
                        "interrupted; replaying from it", replay_path)
            j.truncate(reason="interrupted_replay")
    elif os.path.exists(j.path) and os.path.getsize(j.path) > 0:
        os.replace(j.path, replay_path)
    records, bad, torn = read_records(replay_path)
    if torn:
        log.warning("journal: torn final record in %s (killed "
                    "mid-write) — tolerated", replay_path)
    if bad:
        log.warning("journal: %d corrupt mid-file record(s) in %s "
                    "skipped", bad, replay_path)

    base: Optional[List[Dict]] = None
    base_fp: Optional[Dict] = None
    if checkpoint_path and os.path.exists(checkpoint_path):
        snap = checkpoint.load(checkpoint_path)
        if snap is not None:
            base = snap.get("requests", [])
            base_fp = snap.get("engine")

    recs, findings, header = replay_state(records, base=base)
    for f in findings:
        log.warning("journal replay: %s", f)
    fp = (header or {}).get("fp") or base_fp
    if fp is None:
        # no fingerprint evidence (empty/headerless journal): use the
        # engine's own — replay proceeds, nothing to compare against
        fp = checkpoint._fingerprint(engine)
    snap2 = {"version": checkpoint.SNAPSHOT_VERSION, "engine": fp,
             "requests": recs}
    handles, finished = checkpoint.resume(engine, snap2, strict=strict)
    # retired-but-keyed records: a client retrying with the same
    # idempotency key attaches to the COMPLETED stream instead of
    # re-running it
    seeded = 0
    seed = getattr(engine, "seed_finished_idempotent", None)
    if seed is not None:
        for rec in finished:
            if rec.get("idempotency_key"):
                seed(rec)
                seeded += 1
    # mark the replay consumed IN the fresh journal (after the
    # resubmits re-seeded it): if the sideline removal below fails,
    # the next startup can tell this consumed sideline from a
    # crashed-mid-recovery one and must NOT truncate the live journal
    if os.path.exists(replay_path):
        with j._lock:
            j._append({"rec": "replay_done", "t": time.time()})
            j._sync()
            j._replay_done = True
    resumable = sum(1 for r in recs if checkpoint.is_resumable(r))
    dropped = max(0, resumable - len(handles))
    _REPLAYED.inc(len(handles))
    if dropped:
        _DROPPED.inc(dropped)
    dt = time.perf_counter() - t0
    _REPLAY_SECONDS.observe(dt)
    j.last_replay = {
        "replayed": len(handles), "dropped": dropped,
        "finished": len(finished), "seconds": round(dt, 4),
        "records": len(records), "corrupt_lines": bad,
        "torn_tail": torn, "findings": len(findings),
        "idempotent_seeded": seeded,
    }
    try:
        os.remove(replay_path)
    except FileNotFoundError:
        pass   # fresh startup: no sideline was ever created
    except OSError:
        # sideline it out of the startup path instead; if even that
        # fails, the replay_done marker above keeps the next startup
        # from mis-truncating the live journal
        try:
            os.replace(replay_path, replay_path + ".invalid")
        except OSError:
            log.error("journal: could not remove consumed sideline %s "
                      "(the replay_done marker guards the next "
                      "startup)", replay_path, exc_info=True)
    log.info("journal replay: %d resubmitted, %d finished, %d dropped "
             "in %.3fs (%s)", len(handles), len(finished), dropped, dt,
             j.path)
    return handles, finished
