"""cake-tpu CLI entry point.

Capability parity with `cake-cli` (cake-cli/src/main.rs): parse args, build
the context, then either serve the REST API or run a one-shot generation.
There is no worker mode to dispatch — the reference's master/worker split
(main.rs:28-54) collapses into one SPMD process; `--mode worker` is accepted
and explained for compatibility.
"""

from __future__ import annotations

import logging
import os
import sys


def _serve_multihost(master, args) -> int:
    """Serve the REST API over a mesh that spans processes.

    Under multi-controller SPMD every process must dispatch each engine
    step, so the coordinator publishes one tiny op record per step over a
    TCP control channel and every other host replays it — the reference's
    master→worker request loop (worker.rs:289-303) re-expressed for a
    single SPMD program. Every host runs this same command; process roles
    come from jax.distributed (parallel/distributed.initialize)."""
    import jax

    from cake_tpu.api import start
    from cake_tpu.parallel.distributed import is_coordinator
    from cake_tpu.serve.control import (
        ControlClient, ControlServer, broadcast_control_address,
    )

    image_mode = master.llm is None
    if image_mode:
        # SD multi-host: Context.load_image_model sharded the whole
        # pipeline over a process-spanning ("dp",) mesh, so every
        # process must dispatch each generation's jit sequence. A
        # generation is deterministic from its request args (seed and
        # scheduler ride in them), so ONE op per request suffices:
        # the coordinator publishes the args, followers replay
        # master.generate_image with them (_run_image_follower).
        engine = None
    else:
        fwd = getattr(master.llm, "_forward_fn", None)
        if fwd is not None and getattr(fwd, "_dp", False):
            # dp x sp shards the SLOT axis over dp, so decode outputs
            # (logits/tokens) are dp-sharded — not fully addressable
            # per process, which the engine's multi-host fetch path
            # (replicated-logits localization) cannot consume
            raise ValueError(
                "dp x sp serving is single-host only (dp-sharded "
                "decode outputs are process-local); drop --dp or "
                "serve on one host")
        # every process builds the identical engine (the shared-cache
        # zeros allocation is a global computation, so construction
        # order matters and must match across hosts)
        engine = master.make_engine()
        if engine is None:
            raise ValueError(
                "this serving mode (--draft-model multi-host, or an "
                "sp composition without an engine contract) has no "
                "multi-host step replay; serve it on one host")
        # the pre-fail capture must outlive the heartbeat stale window
        # (the monitor is exactly the late-arriving consumer)
        engine.fail_recs_ttl = args.heartbeat_timeout + 60.0
    # a model without a cross-process placement (no topology/tp/dp/sp)
    # runs entirely inside the coordinator: no step replay needed —
    # followers just idle on the control channel until the stop op,
    # preserving the pre-existing behavior for this configuration. An
    # sp-engined model (custom forward, no (plan, mesh)) IS
    # cross-process: its shard_maps span the global mesh, so every
    # process must replay each step op.
    replayed = (image_mode
                or getattr(master.llm, "parallel", None) is not None
                or getattr(master.llm, "_forward_fn", None) is not None)
    if is_coordinator():
        import os
        import secrets
        import signal
        import threading

        from cake_tpu.parallel.health import ServingHealth

        token = secrets.token_hex(16)
        adv = _advertised_host(args)
        try:
            control = ControlServer(jax.process_count() - 1, host=adv,
                                    token=token)
            bind_host = adv
        except OSError:
            # the advertised name may not be a bindable interface (NAT,
            # aliases); fall back to all interfaces — the token still
            # gates who can become a follower or see ops
            control = ControlServer(jax.process_count() - 1, token=token)
            bind_host = ""
        # failure detection (SURVEY §5): follower heartbeats feed the
        # serving health — a dead host 503s the API instead of letting
        # the next collective hang forever. Image mode serves through
        # the locked path (no engine to watch): no heartbeats, a dead
        # follower surfaces as the next generation's publish error.
        health = None
        hb_adv = ""
        if engine is not None:
            health = ServingHealth(engine,
                                   stall_after_s=args.stall_timeout)
            hb_addr = health.expect_workers(
                [f"proc{i}" for i in range(1, jax.process_count())],
                bind_host=bind_host,
                stale_after_s=args.heartbeat_timeout)
            hb_adv = f"{adv}:{hb_addr.rsplit(':', 1)[1]}"
        # fleet telemetry federation (obs/federation.py): followers
        # ship their metrics/events/applied-seq frames here; the
        # collector feeds /api/v1/fleet, ?host= event filters,
        # host-labeled /metrics families and cross-host timelines.
        # Token-gated with the SAME control secret — cluster members
        # only.
        collector = None
        tel_adv = ""
        tel_enabled, tel_interval = master.telemetry_settings()
        if tel_enabled:
            from cake_tpu.obs.federation import TelemetryCollector
            tel_kwargs = dict(
                token=token, control=control, local_host="proc0",
                stale_after_s=max(args.heartbeat_timeout,
                                  3 * tel_interval),
                max_hosts=max(8, 2 * jax.process_count()))
            try:
                collector = TelemetryCollector(host=bind_host,
                                               **tel_kwargs)
            except OSError:
                # same NAT/alias fallback the control bind takes
                collector = TelemetryCollector(**tel_kwargs)
            tel_adv = f"{adv}:{collector.port}"
        broadcast_control_address(
            f"{adv}:{control.port}|{token}|{hb_adv}|{tel_adv}")
        control.accept_followers()
        # (the collector reaches engine.telemetry — the cross-host
        # timeline merge — through ONE wiring site: ApiServer.__init__,
        # via start(collector=...) below)
        if image_mode:
            master.attach_image_control(control)
        elif replayed:
            engine.attach_control(control)

        done = threading.Event()

        def teardown():
            # ordering matters: stop (publishes the stop op) -> wait for
            # control-socket EOF (the follower's signal that it is about
            # to enter jax.distributed.shutdown()) -> enter our own
            # shutdown. The coordination service's shutdown BARRIER then
            # holds the leader service up until every follower has
            # finished disconnecting — so the leader can never die while
            # a follower is mid-disconnect (which would abort it from
            # its heartbeat thread).
            if done.is_set():
                return
            done.set()
            try:
                if health is not None:
                    health.close()
            except Exception:  # noqa: BLE001
                pass
            if engine is not None:
                engine.stop()
            if engine is None or not replayed:
                # image followers / idle followers never get a stop from
                # an engine; release them explicitly
                try:
                    control.publish({"op": "stop"})
                except Exception:  # noqa: BLE001
                    pass
            control.wait_closed()
            if collector is not None:
                # AFTER wait_closed: the stop op triggers each
                # follower's final exporter flush (terminal applied
                # seq -> lag drains to 0), and the control-socket EOF
                # proves that flush has been sent — only then stop
                # accepting frames
                try:
                    collector.close()
                except Exception:  # noqa: BLE001
                    pass
            control.close()
            _distributed_shutdown()

        def on_sigterm(signum, frame):
            # api.start (checkpoint mode) chains here AFTER its own
            # save_and_exit; exiting 0 replaces the default-handler death
            # that would strand the followers mid-heartbeat
            teardown()
            os._exit(0)

        try:
            signal.signal(signal.SIGTERM, on_sigterm)
        except ValueError:
            pass  # not the main thread; caller owns signals
        try:
            start(master, address=args.api, engine=engine,
                  checkpoint_path=args.checkpoint, health=health,
                  collector=collector,
                  announce=getattr(args, "router_announce", None),
                  announce_interval_s=args.announce_interval,
                  announce_token=os.environ.get("CAKE_ANNOUNCE_TOKEN"))
        finally:
            teardown()
    else:
        from cake_tpu.parallel.health import HeartbeatSender

        payload = broadcast_control_address(None)
        addr, _, rest = payload.partition("|")
        token, _, rest = rest.partition("|")
        hb_addr, _, tel_addr = rest.partition("|")
        client = ControlClient(addr, token=token or None)
        if getattr(args, "fault_plan", None):
            # follower-side chaos: control.recv rules fire in this
            # process (the plan string is identical on every host, so
            # the experiment stays reproducible)
            from cake_tpu.faults import build_injector
            client.faults = build_injector(args.fault_plan)
        proc_name = f"proc{jax.process_index()}"
        beat = (HeartbeatSender(hb_addr, proc_name)
                if hb_addr else None)
        # fleet telemetry exporter (obs/federation.py): the loop below
        # used to be an observability black hole — now this process's
        # metrics registry, event-bus events, step summaries, applied
        # control-op seq and a health snapshot ship to the
        # coordinator's collector every --telemetry-interval seconds
        exporter = None
        tel_enabled, tel_interval = master.telemetry_settings()
        if tel_enabled and tel_addr:
            from cake_tpu.obs.federation import TelemetryExporter

            def _health_snapshot(beat=beat):
                out = {}
                if beat is not None:
                    out["heartbeat_ok"] = beat.alive_within(
                        beat.worst_case_gap_s)
                return out

            exporter = TelemetryExporter(
                tel_addr, host=proc_name, token=token or None,
                interval_s=tel_interval,
                events=getattr(engine, "events", None)
                if engine is not None else None,
                flight=getattr(engine, "flight", None)
                if engine is not None else None,
                applied_seq=(
                    (lambda: engine.applied_op_seq)
                    if engine is not None else None),
                health_snapshot=_health_snapshot)
        try:
            if image_mode:
                _run_image_follower(master, client)
            else:
                # with a cross-process placement this replays every
                # engine step; without one no step ops ever arrive and
                # the loop just blocks until the coordinator's stop.
                # Liveness deadline: a coordinator that dies between
                # ops (no FIN) used to hang this process in recv()
                # forever — quiet intervals now re-check the heartbeat
                # channel (the monitor lives in the coordinator
                # process) and exit with a clear error when it is gone
                # the window must cover the sender's worst-case quiet
                # gap (a monitor blip parks the sender in a capped
                # backoff sleep — it is NOT evidence the coordinator
                # died), else the two features defeat each other
                hb_window = max(args.heartbeat_timeout,
                                beat.worst_case_gap_s
                                if beat is not None else 5.0)
                engine.run_follower_loop(
                    client,
                    op_timeout_s=hb_window if beat is not None else None,
                    liveness=(
                        (lambda: beat.alive_within(hb_window))
                        if beat is not None else None))
        finally:
            if exporter is not None:
                # flush the terminal frame (final applied seq -> the
                # coordinator's fleet lag drains to 0) BEFORE the
                # control-socket EOF below: the coordinator keeps its
                # collector open until that EOF arrives
                exporter.close(flush=True)
            if beat is not None:
                beat.close()
            # socket EOF first, THEN jax.distributed.shutdown() — this
            # order is load-bearing both ways: (a) the coordination
            # service has a shutdown BARRIER (a follower's shutdown()
            # blocks until the leader also enters shutdown), so closing
            # the socket after shutdown would mutual-wait with the
            # coordinator's wait_closed() and stall every clean exit;
            # (b) the same barrier is what keeps the leader service
            # alive until we are fully disconnected — EOF merely tells
            # the coordinator to enter the barrier, which then completes
            # only once we do too, so the leader can never die while we
            # are mid-disconnect
            client.close()
            _distributed_shutdown()
    return 0


def _run_image_follower(master, client) -> None:
    """Image-mode follower: replay whole-generation ops. A generation is
    deterministic from its request args (seed + scheduler ride in them),
    so executing master.generate_image with the coordinator's args
    dispatches the identical jit sequence — the SPMD analog of the
    reference's per-component SD workers (sd.rs:198-302)."""
    import logging as _logging

    from cake_tpu.args import ImageGenerationArgs
    log = _logging.getLogger(__name__)
    log.info("image follower: replaying generation ops")
    while True:
        op = client.recv()
        if op is None or op.get("op") == "stop":
            log.info("image follower: coordinator %s",
                     "stopped" if op else "closed the channel")
            return
        if op.get("op") != "image":
            log.error("image follower: unknown op %r", op.get("op"))
            continue
        try:
            master.generate_image(
                ImageGenerationArgs.from_json(op["args"]),
                lambda _pngs: None)
        except Exception:  # noqa: BLE001
            # a failed replay desyncs the SPMD dispatch; disconnecting
            # makes the coordinator's next publish fail loudly instead
            # of wedging a collective
            log.exception("image follower: generation replay failed; "
                          "disconnecting")
            return


def _distributed_shutdown() -> None:
    try:
        import jax
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 — teardown must never mask the exit
        logging.getLogger(__name__).debug("distributed shutdown failed",
                                          exc_info=True)


def _advertised_host(args) -> str:
    """Address followers use to reach the coordinator's control socket:
    CAKE_CONTROL_HOST, else the host the jax coordinator was reached at
    (CAKE_COORDINATOR), else this host's name."""
    import os
    import socket

    if os.environ.get("CAKE_CONTROL_HOST"):
        return os.environ["CAKE_CONTROL_HOST"]
    coord = os.environ.get("CAKE_COORDINATOR", "")
    if ":" in coord:
        return coord.rsplit(":", 1)[0]
    return socket.gethostname()


def _serve_router(args) -> int:
    """The `cake-router` process role: no model weights, no devices —
    a thin HTTP front door (cake_tpu/router) over --replicas. With a
    --model directory holding tokenizer.json the affinity keys are
    page-aligned token fingerprints (the register_prefix rounding
    rule); otherwise they degrade to system-prompt text fingerprints
    (RouterServer logs the one-shot warning)."""
    import os

    from cake_tpu.args import parse_replicas
    from cake_tpu.router import start_router

    log = logging.getLogger(__name__)
    # with fleet discovery (--router-announce) the static seed is
    # optional — the fleet forms from replica announce frames
    replicas = parse_replicas(args.replicas) if args.replicas else []
    tokenizer = None
    if args.model:
        try:
            from cake_tpu.models.llama.generator import load_tokenizer
            tokenizer = load_tokenizer(args.model)
        except Exception as e:  # noqa: BLE001 — degraded, not fatal
            log.warning("router: could not load tokenizer from %s "
                        "(%s); affinity falls back to text "
                        "fingerprints", args.model, e)
    address = args.api or args.address
    log.info("router: fronting %d replica(s) on %s", len(replicas),
             address)
    start_router(replicas, address=address, tokenizer=tokenizer,
                 poll_interval_s=args.router_poll,
                 load_watermark=args.router_watermark,
                 policy_mode=args.router_policy,
                 # distributed tracing + sentinel (ISSUE 15): the
                 # router reuses the engine's obs flag surface —
                 # hop-span JSONL, typed event ring/log, --sentinel
                 trace_ring=args.trace_ring,
                 trace_events=args.trace_events,
                 event_ring=args.event_ring,
                 event_log=args.event_log,
                 sentinel=args.sentinel,
                 sentinel_interval_s=args.sentinel_interval,
                 # closed-loop anomaly weighting (ISSUE 16,
                 # obs/actions.py): de-weight/re-weight placement from
                 # router-tier anomalies — opt-in, report-only default
                 anomaly_weighting=args.router_anomaly_weighting,
                 # fleet discovery (ISSUE 18, router/discovery.py):
                 # bind the token-gated announce listener; replicas
                 # self-register, pushed frames supersede polling,
                 # departures drain-then-forget
                 announce=args.router_announce,
                 announce_interval_s=args.announce_interval,
                 announce_token=os.environ.get("CAKE_ANNOUNCE_TOKEN"))
    return 0


def router_main(argv=None) -> int:
    """The `cake-router` entry: the front-door role with --router
    implied (equivalent to `cake-tpu --router --replicas ...`); the
    hook a console-script or wrapper shim points at."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--router" not in argv:
        argv = ["--router"] + argv
    return main(argv)


def main(argv=None) -> int:
    from cake_tpu.args import parse_args
    from cake_tpu.master import Master

    logging.basicConfig(
        level=logging.INFO,
        format="[%(asctime)s] %(levelname)s %(name)s: %(message)s",
    )
    args, sd_args, img_args = parse_args(argv)

    if args.router:
        # BEFORE Master.from_args/initialize: the router is a
        # model-less, device-less process role — it must not load
        # weights or join a mesh
        return _serve_router(args)
    if getattr(args, "replicas", None):
        # one-shot warning mirroring --step-log: the replica list only
        # feeds the router role
        logging.getLogger(__name__).warning(
            "--replicas has no effect without --router: the replica "
            "list names the backends of the front-door router "
            "(cake_tpu/router)")

    if getattr(args, "kv_host_pages", None) and not args.kv_pages:
        # one-shot warning mirroring --step-log: the host KV tier
        # spills PAGED pool pages, so without --kv-pages the flag does
        # nothing — say so instead of silently ignoring it
        logging.getLogger(__name__).warning(
            "--kv-host-pages has no effect without --kv-pages: the "
            "host tier spills paged KV pool pages (cake_tpu/kv)")

    if getattr(args, "router_announce", None) and not args.api:
        # same discipline: on a non-router process the flag points the
        # replica's announcer at a router, and only an --api serving
        # process has anything to announce
        logging.getLogger(__name__).warning(
            "--router-announce has no effect without --api (or "
            "--router): a replica announces its serving address to "
            "the front door (cake_tpu/router/discovery.py)")

    if getattr(args, "router_anomaly_weighting", False):
        # same discipline: the weighting actuator lives in the router
        # role's process — on an engine replica the flag does nothing
        logging.getLogger(__name__).warning(
            "--router-anomaly-weighting has no effect without "
            "--router: the placement de-weighting actuator runs in "
            "the front-door process (cake_tpu/router)")

    if (getattr(args, "journal_fsync", "batch") != "batch"
            and not getattr(args, "journal", None)):
        # same discipline: the fsync mode tunes the journal's
        # durability barrier, and without --journal there is no
        # journal to fsync
        logging.getLogger(__name__).warning(
            "--journal-fsync has no effect without --journal: it "
            "tunes the write-ahead request journal's durability "
            "barrier (serve/journal.py)")

    if args.mode == "worker":
        print(
            "cake-tpu runs the whole topology as one SPMD program over the "
            "device mesh; there is no separate worker process. Run in "
            "master mode on the host attached to the TPU slice.",
            file=sys.stderr,
        )
        return 2

    # multi-host: every host runs this same program (SPMD); coordinates
    # auto-detected on TPU pods or taken from CAKE_* env vars
    from cake_tpu.parallel.distributed import initialize
    initialize()

    master = Master.from_args(args, sd_args)

    if args.api:
        import jax

        from cake_tpu.api import start
        if jax.process_count() > 1:
            return _serve_multihost(master, args)
        if getattr(args, "telemetry_export", None):
            # one-shot warning mirroring --step-log: the federation
            # plane ships FOLLOWER telemetry to the coordinator; a
            # single-process deployment has no followers to federate
            logging.getLogger(__name__).warning(
                "--telemetry-export has no effect on single-host "
                "serving: there are no follower processes to "
                "federate (obs/federation.py); /api/v1/fleet will "
                "report only this host")
        start(master, address=args.api, checkpoint_path=args.checkpoint,
              announce=getattr(args, "router_announce", None),
              announce_interval_s=args.announce_interval,
              announce_token=os.environ.get("CAKE_ANNOUNCE_TOKEN"))
        return 0

    if args.step_log:
        # the step flight recorder lives in the serving engine; a
        # one-shot generation has none — be loud instead of writing an
        # empty file the operator then greps in vain
        logging.getLogger(__name__).warning(
            "--step-log applies to engine serving (--api); one-shot "
            "generation records no step flight")
    if getattr(args, "event_log", None) \
            or getattr(args, "slo_targets", None):
        # the event bus and the SLO accountant live in the serving
        # engine; a one-shot generation would write an empty event log
        # and account nothing — mirror the --step-log warning
        logging.getLogger(__name__).warning(
            "--event-log / --slo-targets apply to engine serving "
            "(--api); one-shot generation publishes no events and "
            "accounts no SLOs")
    if args.priority_classes or args.preemption or args.shed:
        # the whole scheduling subsystem lives in the serving engine
        # (priority queues / preemption / shed admission); a one-shot
        # generation has exactly one request and nothing to schedule —
        # be loud instead of the flags silently doing nothing
        logging.getLogger(__name__).warning(
            "--priority-classes / --preemption / --shed apply to "
            "engine serving (--api); one-shot generation runs a "
            "single request with nothing to schedule")
    if args.kv_pages or args.auto_prefix \
            or getattr(args, "kv_host_pages", None) \
            or getattr(args, "kv_dtype", None) in ("int8", "int4") \
            or getattr(args, "mixed_batch", "auto") == "on":
        # all live in the serving engine (paged pool / prefix registry
        # / mixed ragged step / kv tiering); a one-shot generation
        # silently ignoring them would look like the feature "did
        # nothing"
        logging.getLogger(__name__).warning(
            "--kv-pages / --auto-prefix / --mixed-batch / --kv-dtype "
            "int8/int4 / --kv-host-pages apply to engine serving "
            "(--api); one-shot generation uses the sequential "
            "generator's dense cache")
    if getattr(args, "autotune", "off") != "off":
        # the autotuner hot-switches a LIVE engine's config between
        # iterations; a one-shot generation has no engine and no load
        # to adapt to — be loud instead of the flag silently vanishing
        logging.getLogger(__name__).warning(
            "--autotune applies to engine serving (--api); one-shot "
            "generation has no live engine to reconfigure")
    if getattr(args, "telemetry_export", None):
        # the exporter/collector pair lives in multi-host API serving;
        # a one-shot generation federates nothing — be loud instead of
        # the flag silently vanishing
        logging.getLogger(__name__).warning(
            "--telemetry-export applies to multi-host API serving "
            "(--api across processes); one-shot generation runs one "
            "process with nothing to federate")
    if getattr(args, "fault_plan", None) \
            or getattr(args, "recovery", None) is not None:
        # the fault plane's sites and the recovery loop live in the
        # serving engine; a one-shot generation injecting nothing
        # would read as "chaos found no bugs" — be loud instead
        logging.getLogger(__name__).warning(
            "--fault-plan / --recovery apply to engine serving "
            "(--api); one-shot generation dispatches no engine steps "
            "to inject into or recover")
    if getattr(args, "journal", None):
        # the write-ahead request journal records engine admissions
        # and emitted-token batches; a one-shot generation admits
        # nothing through the engine — mirror the --step-log warning
        logging.getLogger(__name__).warning(
            "--journal applies to engine serving (--api); one-shot "
            "generation journals nothing and replays nothing")
    if getattr(args, "disagg", None):
        # the prefill/decode split is a pair of SERVING engines wired
        # by the transfer channel; a one-shot generation has neither —
        # warn AND clear so Master.from_args does not bind/dial a
        # channel no request will ever cross
        logging.getLogger(__name__).warning(
            "--disagg applies to engine serving (--api): a one-shot "
            "generation has no peer to ship KV pages to "
            "(cake_tpu/kv/transfer.py); ignoring it")
        args.disagg = None

    if args.model_type.value == "image":
        count = [0]

        def save(pngs):
            for png in pngs:
                path = f"image_{count[0]}.png"
                with open(path, "wb") as f:
                    f.write(png)
                print(f"wrote {path}")
                count[0] += 1

        master.generate_image(img_args, save)
        return 0

    from cake_tpu.utils.profiling import trace
    with trace(args.tracing):
        master.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
