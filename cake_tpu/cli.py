"""cake-tpu CLI entry point.

Capability parity with `cake-cli` (cake-cli/src/main.rs): parse args, build
the context, then either serve the REST API or run a one-shot generation.
There is no worker mode to dispatch — the reference's master/worker split
(main.rs:28-54) collapses into one SPMD process; `--mode worker` is accepted
and explained for compatibility.
"""

from __future__ import annotations

import logging
import sys


def main(argv=None) -> int:
    from cake_tpu.args import parse_args
    from cake_tpu.master import Master

    logging.basicConfig(
        level=logging.INFO,
        format="[%(asctime)s] %(levelname)s %(name)s: %(message)s",
    )
    args, sd_args, img_args = parse_args(argv)

    if args.mode == "worker":
        print(
            "cake-tpu runs the whole topology as one SPMD program over the "
            "device mesh; there is no separate worker process. Run in "
            "master mode on the host attached to the TPU slice.",
            file=sys.stderr,
        )
        return 2

    # multi-host: every host runs this same program (SPMD); coordinates
    # auto-detected on TPU pods or taken from CAKE_* env vars
    from cake_tpu.parallel.distributed import initialize, is_coordinator
    initialize()

    master = Master.from_args(args, sd_args)

    if args.api:
        from cake_tpu.api import start
        if is_coordinator():
            start(master, address=args.api,
                  checkpoint_path=args.checkpoint)
        else:
            # non-coordinator hosts participate in the SPMD computations
            # driven by the coordinator's engine; they idle here
            import time as _time
            while True:
                _time.sleep(3600)
        return 0

    if args.model_type.value == "image":
        count = [0]

        def save(pngs):
            for png in pngs:
                path = f"image_{count[0]}.png"
                with open(path, "wb") as f:
                    f.write(png)
                print(f"wrote {path}")
                count[0] += 1

        master.generate_image(img_args, save)
        return 0

    from cake_tpu.utils.profiling import trace
    with trace(args.tracing):
        master.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
