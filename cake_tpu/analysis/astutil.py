"""Tiny AST helpers shared by the cakelint checkers."""

from __future__ import annotations

import ast
from typing import Optional, Tuple


def dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('a','b','c') for `a.b.c`, None for anything not a pure
    Name/Attribute chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    """True for `self.X` (optionally a specific X)."""
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))


def expr_key(node: ast.AST) -> str:
    """Structural identity for comparing small expressions (e.g. the
    lock owner in `with eng._switch_lock:` vs the accessed object)."""
    return ast.dump(node)


def is_terminal(stmt: ast.stmt) -> bool:
    """Statement unconditionally leaves the current block."""
    if isinstance(stmt, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
        return True
    if isinstance(stmt, ast.If):
        return (bool(stmt.orelse)
                and block_terminates(stmt.body)
                and block_terminates(stmt.orelse))
    return False


def block_terminates(body) -> bool:
    return bool(body) and is_terminal(body[-1])


def func_symbol(class_name: Optional[str], func_name: str) -> str:
    return f"{class_name}.{func_name}" if class_name else func_name
