"""cakelint core: findings, suppressions, baselines, declarations.

The analyzer is dependency-free (stdlib ast/tokenize only) and runs in
two passes over a file set:

  1. collect — every file is parsed and scanned for *declarations*, the
     in-source vocabulary that drives the checkers:

       ENGINE_THREAD_ATTRS   class attr: dict {attr: lock-or-None} (or a
                             tuple, meaning every attr maps to None) —
                             single-writer engine-thread state; a mapped
                             lock name is the one lock whose holder may
                             touch the attr from a handler thread
       HANDLER_THREAD_METHODS class attr: tuple of method names that run
                             on handler/API/scrape/signal threads
       OPTIONAL_PLANES       class attr: tuple of attr names that hold
                             optional subsystems (None = disabled plane);
                             every dotted use must be `is not None`-guarded
       LOCK_ORDER            class attr: tuple of lock attr names,
                             outermost first — the only legal nesting order
       NO_BLOCKING_UNDER     class attr: tuple of lock attr names under
                             which blocking calls are banned

     plus `@engine_thread_only`-decorated methods (the runtime-assert
     marker from cake_tpu.analysis.annotations).

  2. check — each checker (affinity, guards, locks, jit-purity) walks
     the ASTs against the collected vocabulary and emits Findings.

Suppression grammar (same line as the finding, comment):

    # cakelint: skip[rule] reason text
    # cakelint: skip[rule1,rule2] reason text
    # cakelint: skip[*] reason text

A skip with no reason is itself a finding (`bad-suppression`), as is an
unknown rule name. Baselines store content-addressed fingerprints
(rule + path + normalized source line + duplicate index) so they
survive unrelated line drift.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

RULES = ("affinity", "guards", "locks", "jit-purity")
# core-owned rules (not suppressible targets of themselves)
META_RULES = ("bad-suppression", "parse")

DECL_NAMES = ("ENGINE_THREAD_ATTRS", "HANDLER_THREAD_METHODS",
              "OPTIONAL_PLANES", "LOCK_ORDER", "NO_BLOCKING_UNDER")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = ""          # class.method or function the finding is in

    def fingerprint(self, src_lines: Sequence[str],
                    dup_index: int = 0) -> str:
        text = ""
        if 1 <= self.line <= len(src_lines):
            text = src_lines[self.line - 1].strip()
        # normalize the path so `cake_tpu/`, `./cake_tpu` and the
        # absolute spelling all fingerprint identically (baselines are
        # written and checked from the repo root either way)
        path = os.path.relpath(os.path.abspath(self.path))
        h = hashlib.sha1()
        h.update("\x1f".join(
            (self.rule, path.replace(os.sep, "/"), self.symbol,
             text, str(dup_index))).encode())
        return h.hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "symbol": self.symbol}


@dataclass
class ClassDecl:
    """Vocabulary declared by one class (collect pass)."""
    path: str
    name: str
    engine_attrs: Dict[str, Optional[str]] = field(default_factory=dict)
    handler_methods: Tuple[str, ...] = ()
    planes: Tuple[str, ...] = ()
    lock_order: Tuple[str, ...] = ()
    no_blocking_under: Tuple[str, ...] = ()
    thread_only_methods: Tuple[str, ...] = ()


@dataclass
class FileUnit:
    path: str                 # as reported (relative to the scan root)
    tree: ast.Module
    src_lines: List[str]
    suppressions: Dict[int, Tuple[Tuple[str, ...], str]]  # line -> (rules, reason)


@dataclass
class Vocabulary:
    """Merged cross-file view the checkers consume."""
    classes: List[ClassDecl] = field(default_factory=list)
    # attr -> lock-or-None, merged across every ENGINE_THREAD_ATTRS
    engine_attrs: Dict[str, Optional[str]] = field(default_factory=dict)
    # method names carrying @engine_thread_only anywhere
    thread_only_methods: frozenset = frozenset()
    # lock name -> rank (0 = outermost)
    lock_rank: Dict[str, int] = field(default_factory=dict)
    no_blocking_under: frozenset = frozenset()

    def owner_classes(self) -> List[ClassDecl]:
        return [c for c in self.classes
                if c.engine_attrs or c.thread_only_methods]


def _literal_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _literal_attr_map(node: ast.AST) -> Optional[Dict[str, Optional[str]]]:
    tup = _literal_tuple(node)
    if tup is not None:
        return {a: None for a in tup}
    if isinstance(node, ast.Dict):
        out: Dict[str, Optional[str]] = {}
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                return None
            if isinstance(v, ast.Constant) and (
                    v.value is None or isinstance(v.value, str)):
                out[k.value] = v.value
            else:
                return None
        return out
    return None


def _is_thread_only_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Name):
        return dec.id == "engine_thread_only"
    if isinstance(dec, ast.Attribute):
        return dec.attr == "engine_thread_only"
    return False


def collect_class_decls(path: str, tree: ast.Module) -> List[ClassDecl]:
    decls = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        d = ClassDecl(path=path, name=node.name)
        thread_only = []
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                if name == "ENGINE_THREAD_ATTRS":
                    d.engine_attrs = _literal_attr_map(stmt.value) or {}
                elif name == "HANDLER_THREAD_METHODS":
                    d.handler_methods = _literal_tuple(stmt.value) or ()
                elif name == "OPTIONAL_PLANES":
                    d.planes = _literal_tuple(stmt.value) or ()
                elif name == "LOCK_ORDER":
                    d.lock_order = _literal_tuple(stmt.value) or ()
                elif name == "NO_BLOCKING_UNDER":
                    d.no_blocking_under = _literal_tuple(stmt.value) or ()
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_thread_only_decorator(dc)
                       for dc in stmt.decorator_list):
                    thread_only.append(stmt.name)
        d.thread_only_methods = tuple(thread_only)
        if (d.engine_attrs or d.handler_methods or d.planes
                or d.lock_order or d.no_blocking_under
                or d.thread_only_methods):
            decls.append(d)
    return decls


def build_vocabulary(units: Sequence[FileUnit]) -> Tuple[Vocabulary,
                                                         List[Finding]]:
    vocab = Vocabulary()
    findings: List[Finding] = []
    orders: List[Tuple[str, Tuple[str, ...]]] = []
    thread_only: set = set()
    no_block: set = set()
    for u in units:
        for d in collect_class_decls(u.path, u.tree):
            vocab.classes.append(d)
            vocab.engine_attrs.update(d.engine_attrs)
            thread_only.update(d.thread_only_methods)
            no_block.update(d.no_blocking_under)
            if d.lock_order:
                orders.append((u.path, d.lock_order))
    # merge lock orders; two declarations that disagree on relative
    # order are a configuration error worth failing loudly on
    merged: List[str] = []
    for path, order in orders:
        for name in order:
            if name not in merged:
                merged.append(name)
        ranks = {n: i for i, n in enumerate(merged)}
        prev = -1
        for name in order:
            if ranks[name] < prev:
                findings.append(Finding(
                    "locks", path, 1, 0,
                    f"conflicting LOCK_ORDER declarations: {order!r} "
                    f"disagrees with previously declared order "
                    f"{tuple(merged)!r}"))
                break
            prev = ranks[name]
    vocab.lock_rank = {n: i for i, n in enumerate(merged)}
    vocab.thread_only_methods = frozenset(thread_only)
    vocab.no_blocking_under = frozenset(no_block)
    return vocab, findings


# -- suppressions ------------------------------------------------------------

_SKIP_PREFIX = "cakelint:"


def parse_suppressions(src: str, path: str):
    """(line -> (rules, reason), findings-for-malformed-skips)."""
    supp: Dict[int, Tuple[Tuple[str, ...], str]] = {}
    findings: List[Finding] = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        comments = []
    for line, text in comments:
        body = text.lstrip("#").strip()
        if not body.startswith(_SKIP_PREFIX):
            continue
        directive = body[len(_SKIP_PREFIX):].strip()
        if not directive.startswith("skip["):
            findings.append(Finding(
                "bad-suppression", path, line, 0,
                f"unrecognized cakelint directive {directive!r} "
                "(grammar: `# cakelint: skip[rule] reason`)"))
            continue
        end = directive.find("]")
        if end < 0:
            findings.append(Finding(
                "bad-suppression", path, line, 0,
                "unterminated rule list in cakelint skip"))
            continue
        rules = tuple(r.strip() for r in directive[5:end].split(",")
                      if r.strip())
        reason = directive[end + 1:].strip()
        bad = [r for r in rules if r != "*" and r not in RULES]
        if not rules or bad:
            findings.append(Finding(
                "bad-suppression", path, line, 0,
                f"unknown rule(s) {bad or ['<empty>']} in cakelint skip "
                f"(known: {', '.join(RULES)}, or *)"))
            continue
        if not reason:
            findings.append(Finding(
                "bad-suppression", path, line, 0,
                f"cakelint skip[{','.join(rules)}] carries no reason — "
                "every suppression must say why the exception is safe"))
            continue
        supp[line] = (rules, reason)
    return supp, findings


def _suppressed(f: Finding, unit: FileUnit) -> bool:
    # a directive covers its own line (trailing comment) and the line
    # below it (standalone comment line, where long reasons fit)
    for ent in (unit.suppressions.get(f.line),
                unit.suppressions.get(f.line - 1)):
        if ent is not None:
            rules, _reason = ent
            if "*" in rules or f.rule in rules:
                return True
    return False


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> set:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("version") != 1:
        raise ValueError(f"unsupported baseline format in {path}")
    return set(data.get("fingerprints", ()))


def write_baseline(path: str, fingerprints: Sequence[str]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1,
                   "fingerprints": sorted(set(fingerprints))},
                  fh, indent=1, sort_keys=True)
        fh.write("\n")


def assign_fingerprints(findings: Sequence[Finding],
                        units: Dict[str, FileUnit]) -> List[str]:
    """Stable content fingerprints; duplicates on identical lines get an
    occurrence index so a baseline can hold N-of-a-kind."""
    seen: Dict[str, int] = {}
    out = []
    for f in findings:
        u = units.get(f.path)
        lines = u.src_lines if u is not None else []
        base = f.fingerprint(lines, 0)
        idx = seen.get(base, 0)
        seen[base] = idx + 1
        out.append(f.fingerprint(lines, idx) if idx else base)
    return out


# -- driver ------------------------------------------------------------------

def iter_python_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """[(display_path, abs_path)] for .py files under the given paths,
    skipping caches/hidden dirs."""
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append((p, os.path.abspath(p)))
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if not d.startswith(".")
                             and d != "__pycache__")
            for fn in sorted(files):
                if fn.endswith(".py"):
                    full = os.path.join(root, fn)
                    out.append((full, os.path.abspath(full)))
    return out


def analyze(paths: Sequence[str], rules: Optional[Sequence[str]] = None,
            baseline: Optional[set] = None) -> dict:
    """Run the collect+check passes. Returns a report dict:

      findings      unsuppressed, unbaselined Finding objects
      fingerprints  aligned with findings
      suppressed / baselined   counts
      sites         per-rule count of checked use sites (a checker that
                    saw zero sites cannot vacuously pass a gate test)
      files         number of files parsed
    """
    from cake_tpu.analysis import affinity, guards, locks, purity
    checkers = {"affinity": affinity, "guards": guards,
                "locks": locks, "jit-purity": purity}
    active = list(rules) if rules else list(RULES)
    for r in active:
        if r not in checkers:
            raise ValueError(f"unknown rule {r!r} (known: "
                             f"{', '.join(RULES)})")

    units: Dict[str, FileUnit] = {}
    findings: List[Finding] = []
    for disp, full in iter_python_files(paths):
        try:
            src = open(full, encoding="utf-8").read()
            tree = ast.parse(src, filename=disp)
        except (OSError, SyntaxError, ValueError) as e:
            findings.append(Finding(
                "parse", disp, getattr(e, "lineno", 1) or 1, 0,
                f"could not parse: {e}"))
            continue
        supp, supp_findings = parse_suppressions(src, disp)
        findings.extend(supp_findings)
        units[disp] = FileUnit(path=disp, tree=tree,
                               src_lines=src.splitlines(),
                               suppressions=supp)

    ordered = list(units.values())
    vocab, vocab_findings = build_vocabulary(ordered)
    findings.extend(vocab_findings)

    sites: Dict[str, int] = {}
    for rule in active:
        mod = checkers[rule]
        got, n_sites = mod.check(vocab, ordered)
        sites[rule] = n_sites
        findings.extend(got)

    kept: List[Finding] = []
    n_supp = 0
    for f in findings:
        u = units.get(f.path)
        if u is not None and f.rule not in META_RULES \
                and _suppressed(f, u):
            n_supp += 1
            continue
        kept.append(f)

    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    fps = assign_fingerprints(kept, units)
    n_base = 0
    if baseline:
        filtered, ffps = [], []
        for f, fp in zip(kept, fps):
            if fp in baseline:
                n_base += 1
            else:
                filtered.append(f)
                ffps.append(fp)
        kept, fps = filtered, ffps

    counts: Dict[str, int] = {}
    for f in kept:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {"findings": kept, "fingerprints": fps, "counts": counts,
            "suppressed": n_supp, "baselined": n_base,
            "sites": sites, "files": len(units)}
