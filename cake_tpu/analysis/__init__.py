"""cakelint — AST-level concurrency & dispatch-discipline analyzer.

Static half: tools/cakelint.py drives `analyze()` over cake_tpu/ with
four checkers (affinity, guards, locks, jit-purity) plus a shared
suppression/baseline core — see cake_tpu/analysis/core.py for the
in-source declaration vocabulary. Runtime half: the
`@engine_thread_only` decorator (annotations.py), armed by
CAKE_THREAD_ASSERTS, backstops the affinity rule dynamically.

This package import stays cheap (stdlib only) because serving code
imports the decorator from here.
"""

from cake_tpu.analysis.annotations import (  # noqa: F401
    ASSERT_ENV, WrongThreadError, engine_thread_only,
    thread_asserts_enabled,
)

__all__ = ["engine_thread_only", "WrongThreadError", "ASSERT_ENV",
           "thread_asserts_enabled", "analyze"]


def analyze(paths, rules=None, baseline=None):
    """Lazy alias for cake_tpu.analysis.core.analyze (keeps ast/tokenize
    out of the serving import path)."""
    from cake_tpu.analysis import core
    return core.analyze(paths, rules=rules, baseline=baseline)
