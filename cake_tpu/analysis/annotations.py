"""Runtime half of the cakelint thread-affinity vocabulary.

The static checker (cake_tpu/analysis/affinity.py, driven by
tools/cakelint.py) proves that declared handler-thread entry points only
reach engine-thread-only state through `_run_on_engine_thread` or a
declared lock. This module is the *dynamic backstop*: methods decorated
`@engine_thread_only` assert — when CAKE_THREAD_ASSERTS is set, as
tier-1 does via tests/conftest.py — that they are actually executing on
their owner's engine thread. Off (the production default) the decorator
returns the function unchanged, so the backstop costs nothing: not a
wrapper frame, not an env read per call.

The ownership probe is `self._thread` (the engine's thread handle, see
serve/engine.py start()). A dead or not-yet-started owner passes: the
pre-start direct-drive paths (tests, checkpoint restore) and the
post-join inline teardown in stop()/cancel() are single-threaded by
construction, which is exactly the affinity claim.
"""

from __future__ import annotations

import functools
import os
import threading

# set to any non-empty value other than 0/false/off to arm the asserts
ASSERT_ENV = "CAKE_THREAD_ASSERTS"

# marker the static checker keys on; also set on the wrapper so
# introspection works in both modes
MARKER = "__engine_thread_only__"


def thread_asserts_enabled() -> bool:
    return os.environ.get(ASSERT_ENV, "").lower() not in (
        "", "0", "false", "off")


class WrongThreadError(AssertionError):
    """An @engine_thread_only method ran on a foreign thread while the
    engine thread was alive (a thread-affinity violation the static
    checker could not see — e.g. a call through getattr)."""


def engine_thread_only(fn):
    """Declare a method engine-thread-only.

    Statically: cakelint's affinity checker flags any call to this
    method from a declared handler-thread entry point that is not routed
    through `_run_on_engine_thread` (suppressible with a written
    reason). Dynamically (CAKE_THREAD_ASSERTS): raises WrongThreadError
    when invoked off the owner thread while that thread is alive.
    """
    setattr(fn, MARKER, True)
    if not thread_asserts_enabled():
        # zero-cost no-op: the undecorated function itself
        return fn

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        owner = getattr(self, "_thread", None)
        if owner is not None and owner.is_alive():
            cur = threading.current_thread()
            if cur is not owner:
                raise WrongThreadError(
                    f"{type(self).__name__}.{fn.__name__} is "
                    f"engine-thread-only but ran on {cur.name!r} while "
                    f"engine thread {owner.name!r} is alive (route it "
                    "through _run_on_engine_thread or a declared lock)")
        return fn(self, *args, **kwargs)

    setattr(wrapper, MARKER, True)
    return wrapper
