"""cakelint `jit-purity`: side-effect hygiene inside jitted step fns.

A function is "jitted" when it is decorated `@jax.jit` / `@jit` /
`@partial(jax.jit, ...)` (any partial spelling), or defined locally and
wrapped as `name = jax.jit(fn)`. Under trace such a function runs ONCE
per signature — host side effects in its body are retrace hazards the
flight recorder (obs/steps.py) only catches after the fact:

  * `self.X = ...` / `self.X += ...` — mutating Python state under
    trace bakes the first trace's value in and silently diverges on
    cache hits;
  * `global` declarations (module-state mutation under trace);
  * `time.*` / `random.*` / `np.random.*` calls — traced once, frozen
    forever (use jax.random with a threaded key);
  * `print(...)` — fires at trace time only; `jax.debug.print` is the
    traced-aware spelling and is allowed.

Nested functions handed to host-callback APIs (`jax.pure_callback`,
`io_callback`, `jax.debug.callback`) are exempt: they execute on the
host by design.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from cake_tpu.analysis.astutil import dotted, func_symbol
from cake_tpu.analysis.core import Finding, Vocabulary

RULE = "jit-purity"

_PARTIAL_NAMES = {"partial", "_partial"}


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / _jax.jit / jit / pjit as a bare callable reference."""
    chain = dotted(node)
    return chain is not None and chain[-1] in ("jit", "pjit")


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _is_jit_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
        fchain = dotted(dec.func)
        if fchain and fchain[-1] in _PARTIAL_NAMES and dec.args:
            return _is_jit_expr(dec.args[0])
        # @jax.jit(...) called-decorator form
        if _is_jit_expr(dec.func):
            return True
    return False


def _callback_exempt_ids(fn: ast.AST) -> Set[int]:
    """Subtrees passed to host-callback APIs."""
    out: Set[int] = set()
    names: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = dotted(node.func)
        if not chain or "callback" not in chain[-1]:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                out.add(id(arg))
            elif isinstance(arg, ast.Name):
                names.add(arg.id)
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in names:
            out.add(id(node))
    return out


class _BodyChecker:
    def __init__(self, path: str, symbol: str,
                 findings: List[Finding]):
        self.path = path
        self.symbol = symbol
        self.findings = findings

    def _flag(self, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(
            RULE, self.path, node.lineno, node.col_offset,
            f"{msg} inside jitted {self.symbol} (runs at trace time "
            "only; a cached signature replays the stale value)",
            symbol=self.symbol))

    def run(self, fn: ast.AST) -> None:
        exempt = _callback_exempt_ids(fn)

        def visit(node: ast.AST) -> None:
            if id(node) in exempt:
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                # flatten tuple/list/starred unpacking so
                # `self.n, out = f(x)` is seen like `self.n = ...`
                flat = []
                while targets:
                    t = targets.pop()
                    if isinstance(t, (ast.Tuple, ast.List)):
                        targets.extend(t.elts)
                    elif isinstance(t, ast.Starred):
                        targets.append(t.value)
                    else:
                        flat.append(t)
                for t in flat:
                    base = t
                    while isinstance(base, (ast.Subscript, ast.Attribute)):
                        if isinstance(base, ast.Attribute) \
                                and isinstance(base.value, ast.Name) \
                                and base.value.id == "self":
                            self._flag(t, "mutation of self."
                                          f"{base.attr}")
                            break
                        base = base.value
            elif isinstance(node, ast.Global):
                self._flag(node, "`global " + ", ".join(node.names)
                           + "` mutation")
            elif isinstance(node, ast.Call):
                chain = dotted(node.func)
                if chain:
                    if chain == ("print",):
                        self._flag(node, "print() call (use "
                                         "jax.debug.print)")
                    elif chain[0] == "time":
                        self._flag(node, f"{'.'.join(chain)}() call")
                    elif chain[0] == "random" or (
                            len(chain) >= 2
                            and chain[0] in ("np", "numpy")
                            and chain[1] == "random"):
                        self._flag(node, f"{'.'.join(chain)}() call "
                                   "(thread a jax.random key instead)")
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body if not isinstance(fn, ast.Lambda) else [fn.body]:
            visit(stmt)


def _jitted_functions(tree: ast.Module):
    """Yield (node, name) for every jitted def/lambda in the module."""
    wrapped_names: Set[str] = set()
    lambdas: List[Tuple[ast.Lambda, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func) \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                wrapped_names.add(arg.id)
            elif isinstance(arg, ast.Lambda):
                lambdas.append((arg, f"<lambda:{arg.lineno}>"))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list) \
                    or node.name in wrapped_names:
                yield node, node.name
    for lam, name in lambdas:
        yield lam, name


def check(vocab: Vocabulary, units) -> Tuple[List[Finding], int]:
    findings: List[Finding] = []
    sites = 0
    for unit in units:
        # map defs to their classes for symbol names
        cls_of = {}
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.ClassDef):
                for fn in node.body:
                    cls_of[id(fn)] = node.name
        for fn, name in _jitted_functions(unit.tree):
            sites += 1
            symbol = func_symbol(cls_of.get(id(fn)), name)
            _BodyChecker(unit.path, symbol, findings).run(fn)
    return findings, sites
