"""cakelint `locks`: lock ordering and hold-time discipline.

Driven by two declarations (serve/engine.py):

    LOCK_ORDER = ("_switch_lock", "_rid_lock", "_ckpt_lock")
    NO_BLOCKING_UNDER = ("_rid_lock",)

Enforced, lexically per function plus one level of same-class calls:

  * nested `with` acquires must follow the declared order — taking an
    earlier (or the same — threading.Lock is not reentrant) lock while
    holding a later one is flagged;
  * calling a same-class method that itself acquires lock M while
    lexically holding lock H with rank(M) <= rank(H) is flagged (the
    one-level call-graph closure that catches `submit -> helper` nests);
  * known blocking calls — time.sleep, device_get / block_until_ready
    fetches, socket recv/send/accept/connect, Event.wait / Thread.join,
    select — are banned while holding any NO_BLOCKING_UNDER lock: that
    lock sits on the submit/emit hot path and a sleeper under it stalls
    every handler thread.

Lock identity is by attribute NAME (any owner object): the declared
names are distinctive by convention, which also lets the checker see
`with engine._ckpt_lock:` from the checkpoint module.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from cake_tpu.analysis.astutil import dotted, func_symbol
from cake_tpu.analysis.core import Finding, Vocabulary

RULE = "locks"

# (first segment, last segment) exact pairs
_BLOCKING_CHAINS = {("time", "sleep"), ("select", "select")}
# any call whose final attribute is one of these
_BLOCKING_ATTRS = {"device_get", "block_until_ready", "recv", "recvfrom",
                   "accept", "connect", "sendall", "wait", "join"}


def _lock_name(expr: ast.AST, ranks: Dict[str, int]) -> Optional[str]:
    if isinstance(expr, ast.Attribute) and expr.attr in ranks:
        return expr.attr
    if isinstance(expr, ast.Name) and expr.id in ranks:
        return expr.id
    return None


def _blocking_call(node: ast.Call) -> Optional[str]:
    chain = dotted(node.func)
    if chain is None:
        return None
    if len(chain) >= 2 and (chain[0], chain[-1]) in _BLOCKING_CHAINS:
        return ".".join(chain)
    if len(chain) >= 2 and chain[-1] in _BLOCKING_ATTRS:
        return ".".join(chain)
    return None


def _method_acquires(fn: ast.AST, ranks: Dict[str, int]) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = _lock_name(item.context_expr, ranks)
                if name:
                    out.add(name)
    return out


class _Walker:
    def __init__(self, path: str, symbol: str, vocab: Vocabulary,
                 acquires: Dict[str, Set[str]], findings: List[Finding]):
        self.path = path
        self.symbol = symbol
        self.ranks = vocab.lock_rank
        self.no_block = vocab.no_blocking_under
        self.acquires = acquires     # same-class method -> locks taken
        self.findings = findings
        self.sites = 0

    def walk(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def does not run under the enclosing with; it is
            # walked separately by the top-level pass
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                name = _lock_name(item.context_expr, self.ranks)
                self.walk(item.context_expr, held)
                if name is None:
                    continue
                self.sites += 1
                for h in new_held:
                    if self.ranks[name] == self.ranks[h]:
                        self.findings.append(Finding(
                            RULE, self.path, item.context_expr.lineno,
                            item.context_expr.col_offset,
                            f"re-acquiring held lock {name} "
                            "(threading.Lock is not reentrant: this "
                            "deadlocks)", symbol=self.symbol))
                        break
                    if self.ranks[name] < self.ranks[h]:
                        self.findings.append(Finding(
                            RULE, self.path, item.context_expr.lineno,
                            item.context_expr.col_offset,
                            f"lock order violation: acquiring {name} "
                            f"while holding {h} (declared order: "
                            f"{' -> '.join(sorted(self.ranks, key=self.ranks.get))})",
                            symbol=self.symbol))
                        break
                new_held = new_held + (name,)
            for stmt in node.body:
                self.walk(stmt, new_held)
            return
        if isinstance(node, ast.Call) and held:
            blocked = [h for h in held if h in self.no_block]
            if blocked:
                what = _blocking_call(node)
                if what is not None:
                    self.findings.append(Finding(
                        RULE, self.path, node.lineno, node.col_offset,
                        f"blocking call {what}() while holding "
                        f"{blocked[-1]} (hot-path lock: no sleeps, "
                        "device fetches or socket I/O under it)",
                        symbol=self.symbol))
            # one-level call closure: self.m() where m acquires locks
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "self" \
                    and fn.attr in self.acquires:
                for lock in sorted(self.acquires[fn.attr]):
                    worst = None
                    for h in held:
                        if self.ranks[lock] <= self.ranks[h]:
                            worst = h
                            break
                    if worst is not None:
                        kind = ("re-acquires" if self.ranks[lock]
                                == self.ranks[worst] else
                                "acquires out of order")
                        self.findings.append(Finding(
                            RULE, self.path, node.lineno,
                            node.col_offset,
                            f"call to self.{fn.attr}() {kind} lock "
                            f"{lock} while holding {worst}",
                            symbol=self.symbol))
        for child in ast.iter_child_nodes(node):
            self.walk(child, held)


def check(vocab: Vocabulary, units) -> Tuple[List[Finding], int]:
    findings: List[Finding] = []
    sites = 0
    if not vocab.lock_rank:
        return findings, sites
    for unit in units:
        # same-class one-level call map
        class_of: Dict[int, Optional[str]] = {}
        acquires_by_class: Dict[Optional[str], Dict[str, Set[str]]] = {}
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.ClassDef):
                table: Dict[str, Set[str]] = {}
                for fn in node.body:
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        class_of[id(fn)] = node.name
                        locks = _method_acquires(fn, vocab.lock_rank)
                        if locks:
                            table[fn.name] = locks
                acquires_by_class[node.name] = table

        def top_funcs(tree):
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield node

        for fn in top_funcs(unit.tree):
            cls = class_of.get(id(fn))
            w = _Walker(unit.path, func_symbol(cls, fn.name), vocab,
                        acquires_by_class.get(cls, {}), findings)
            for stmt in fn.body:
                w.walk(stmt, ())
            sites += w.sites
    return findings, sites
