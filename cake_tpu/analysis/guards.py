"""cakelint `guards`: optional-plane access discipline.

A class that declares `OPTIONAL_PLANES = ("_faults", "events", ...)`
promises that each named attribute is either a live subsystem or None
(disabled plane), and that *every* dereference — `self._faults.check()`,
`self.events.publish()`, `self._journal.path`, `self._host_tier[...]` —
is dominated by an `is not None` test on the same attribute, so a
disabled plane costs exactly one attribute read per site.

Recognized guard shapes (lexical, per function):

    if self.P is not None: <use>
    if self.P is None: return/raise/continue/break
    ... <use>                      # after the terminal early-exit
    if self.P is None or other: return
    assert self.P is not None
    self.P.x if self.P is not None else y
    self.P is not None and self.P.x(...)
    self.P is None or self.P.x(...)
    while self.P is not None: <use>

`__init__` is exempt: construction is where planes are wired, and its
assignments (`self._journal.owner = self`) happen in the arm that just
created the plane. Aliased uses (`ev = self.events; ev.publish(...)`)
are invisible to this rule by design — the discipline is *direct dotted
access under a visible guard*, which is what keeps the convention
greppable and the disabled-plane cost one attribute test.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from cake_tpu.analysis.astutil import (
    block_terminates, func_symbol, is_self_attr,
)
from cake_tpu.analysis.core import Finding, Vocabulary

RULE = "guards"


def _plane_of(node: ast.AST, planes: frozenset):
    if isinstance(node, ast.Attribute) and is_self_attr(node) \
            and node.attr in planes:
        return node.attr
    return None


class _FuncChecker:
    def __init__(self, path: str, symbol: str, planes: frozenset,
                 findings: List[Finding]):
        self.path = path
        self.symbol = symbol
        self.planes = planes
        self.findings = findings
        self.sites = 0

    # -- guard extraction ---------------------------------------------------

    def _pos_guards(self, test: ast.AST) -> Set[str]:
        """Planes proven non-None when `test` is truthy."""
        out: Set[str] = set()
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.ops[0], ast.IsNot) \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            p = _plane_of(test.left, self.planes)
            if p:
                out.add(p)
        elif isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            for op in test.values:
                out |= self._pos_guards(op)
        return out

    def _neg_guards(self, test: ast.AST) -> Set[str]:
        """Planes proven non-None when `test` is FALSY (i.e. the test
        checked `P is None`, possibly inside an or-chain)."""
        out: Set[str] = set()
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.ops[0], ast.Is) \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            p = _plane_of(test.left, self.planes)
            if p:
                out.add(p)
        elif isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
            for op in test.values:
                out |= self._neg_guards(op)
        return out

    # -- walking ------------------------------------------------------------

    def run(self, body: List[ast.stmt]) -> None:
        self._block(body, set())

    def _block(self, body: List[ast.stmt], guards: Set[str]) -> None:
        acc = set(guards)
        for stmt in body:
            self._stmt(stmt, acc)
            # a terminal `if P is None:` arm proves P for the rest of
            # the block; assert likewise
            if isinstance(stmt, ast.If) and block_terminates(stmt.body) \
                    and not stmt.orelse:
                acc |= self._neg_guards(stmt.test)
            elif isinstance(stmt, ast.Assert):
                acc |= self._pos_guards(stmt.test)

    def _stmt(self, stmt: ast.stmt, guards: Set[str]) -> None:
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, guards)
            self._block(stmt.body, guards | self._pos_guards(stmt.test))
            self._block(stmt.orelse, guards | self._neg_guards(stmt.test))
        elif isinstance(stmt, ast.While):
            self._expr(stmt.test, guards)
            self._block(stmt.body, guards | self._pos_guards(stmt.test))
            self._block(stmt.orelse, guards)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, guards)
            self._expr(stmt.target, guards)
            self._block(stmt.body, guards)
            self._block(stmt.orelse, guards)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, guards)
            self._block(stmt.body, guards)
        elif isinstance(stmt, ast.Try):
            self._block(stmt.body, guards)
            for h in stmt.handlers:
                self._block(h.body, guards)
            self._block(stmt.orelse, guards)
            self._block(stmt.finalbody, guards)
        elif isinstance(stmt, ast.Assert):
            self._expr(stmt.test, guards)
            if stmt.msg is not None:
                self._expr(stmt.msg, guards | self._pos_guards(stmt.test))
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested function: no dominating-guard inheritance — it may
            # run later, when the plane has been swapped
            self._block(stmt.body, set())
        elif isinstance(stmt, ast.ClassDef):
            self._block(stmt.body, set())
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, guards)

    def _expr(self, node: ast.AST, guards: Set[str]) -> None:
        if isinstance(node, ast.BoolOp):
            live = set(guards)
            for op in node.values:
                self._expr(op, live)
                if isinstance(node.op, ast.And):
                    live |= self._pos_guards(op)
                else:
                    live |= self._neg_guards(op)
            return
        if isinstance(node, ast.IfExp):
            self._expr(node.test, guards)
            self._expr(node.body, guards | self._pos_guards(node.test))
            self._expr(node.orelse, guards | self._neg_guards(node.test))
            return
        if isinstance(node, ast.Lambda):
            self._expr(node.body, set())
            return
        # the dereference itself: self.P.attr / self.P[...] / self.P(...)
        inner = None
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            inner = _plane_of(node.value, self.planes)
        elif isinstance(node, ast.Call):
            inner = _plane_of(node.func, self.planes)
        if inner is not None:
            self.sites += 1
            if inner not in guards:
                ref = node.value if isinstance(
                    node, (ast.Attribute, ast.Subscript)) else node.func
                use = (node.attr if isinstance(node, ast.Attribute)
                       else "[...]" if isinstance(node, ast.Subscript)
                       else "(…)")
                self.findings.append(Finding(
                    RULE, self.path, ref.lineno, ref.col_offset,
                    f"self.{inner}.{use}: optional plane {inner!r} "
                    "dereferenced without a dominating `is not None` "
                    "guard (a disabled plane must cost one attribute "
                    "test per site)",
                    symbol=self.symbol))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword)):
                self._expr(child, guards)
            elif isinstance(child, ast.comprehension):
                self._expr(child.iter, guards)
                for cond in child.ifs:
                    self._expr(cond, guards)


def check(vocab: Vocabulary, units) -> Tuple[List[Finding], int]:
    findings: List[Finding] = []
    sites = 0
    declared = {(c.path, c.name): c for c in vocab.classes if c.planes}
    for unit in units:
        for node in ast.walk(unit.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            decl = declared.get((unit.path, node.name))
            if decl is None:
                continue
            planes = frozenset(decl.planes)
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name in ("__init__", "__post_init__"):
                    continue
                fc = _FuncChecker(unit.path,
                                  func_symbol(node.name, fn.name),
                                  planes, findings)
                fc.run(fn.body)
                sites += fc.sites
    return findings, sites
