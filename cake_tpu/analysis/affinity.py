"""cakelint `affinity`: thread-affinity discipline.

A class that declares

    ENGINE_THREAD_ATTRS = {"_slot_req": None, "_pager": "_switch_lock"}
    HANDLER_THREAD_METHODS = ("submit", "cancel", ...)

promises that the named attributes are single-writer engine-thread
state. The checker then enforces, statically:

  * inside each HANDLER_THREAD_METHODS entry point, a declared attr may
    only be reached (read OR written) under `with self.<declared lock>:`
    for attrs mapped to a lock, or inside a closure handed to
    `self._run_on_engine_thread(...)` (which executes it on the engine
    thread); attrs mapped to None have no lock that legalizes them;
  * a handler entry point may not call an `@engine_thread_only` method
    directly — only via `_run_on_engine_thread`;
  * every OTHER analyzed module (API server, scrape refreshers,
    checkpoint, tools): any dotted access `<obj>.<declared attr>` on a
    non-self object is flagged unless it sits under
    `with <obj>.<declared lock>:` on the same object.

Methods of the owning class outside HANDLER_THREAD_METHODS are treated
as engine-thread context and not checked — the guarantee is that every
declared non-engine entry surface is clean, with the runtime assert
mode (cake_tpu.analysis.annotations, CAKE_THREAD_ASSERTS) backstopping
paths the lexical analysis cannot see.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from cake_tpu.analysis.astutil import expr_key, func_symbol, is_self_attr
from cake_tpu.analysis.core import ClassDecl, Finding, Vocabulary

RULE = "affinity"

ROUTER = "_run_on_engine_thread"


def _exempt_subtrees(fn: ast.AST) -> Tuple[Set[int], Set[str]]:
    """AST node ids of closures routed to the engine thread, plus names
    of nested defs so routed."""
    nodes: Set[int] = set()
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and is_self_attr(node.func, ROUTER) \
                and node.args:
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                nodes.add(id(target))
            elif isinstance(target, ast.Name):
                names.add(target.id)
            elif isinstance(target, ast.Call):
                # partial(fn, ...) / functools.partial(fn, ...)
                for arg in target.args:
                    if isinstance(arg, ast.Name):
                        names.add(arg.id)
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in names:
            nodes.add(id(node))
    return nodes, names


class _HandlerWalker:
    """Walk one handler-thread method, tracking held locks."""

    def __init__(self, path: str, symbol: str, decl: ClassDecl,
                 vocab: Vocabulary, findings: List[Finding]):
        self.path = path
        self.symbol = symbol
        self.decl = decl
        self.vocab = vocab
        self.findings = findings
        self.sites = 0
        self.exempt: Set[int] = set()
        self.thread_only = (set(decl.thread_only_methods)
                            | set(vocab.thread_only_methods))

    def run(self, fn: ast.FunctionDef) -> None:
        self.exempt, _names = _exempt_subtrees(fn)
        for stmt in fn.body:
            self._visit(stmt, frozenset())

    def _visit(self, node: ast.AST, held: frozenset) -> None:
        if id(node) in self.exempt:
            return                       # runs on the engine thread
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and held:
            # a closure defined under a lock does NOT run under it: it
            # may fire later on any thread, so its body is checked with
            # no locks held (mirrors guards.py's nested-def reset)
            body = (node.body if isinstance(node.body, list)
                    else [node.body])
            for stmt in body:
                self._visit(stmt, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set(held)
            for item in node.items:
                if is_self_attr(item.context_expr):
                    acquired.add(item.context_expr.attr)
                self._visit(item.context_expr, held)
            for stmt in node.body:
                self._visit(stmt, frozenset(acquired))
            return
        if isinstance(node, ast.Attribute) and is_self_attr(node) \
                and node.attr in self.decl.engine_attrs:
            self.sites += 1
            lock = self.decl.engine_attrs[node.attr]
            if lock is None or lock not in held:
                want = (f"`with self.{lock}:`" if lock
                        else "no lock grants handler access")
                self.findings.append(Finding(
                    RULE, self.path, node.lineno, node.col_offset,
                    f"self.{node.attr} is engine-thread state touched "
                    f"from handler entry point {self.symbol} ({want}; "
                    "route it through _run_on_engine_thread)",
                    symbol=self.symbol))
        if isinstance(node, ast.Call) and is_self_attr(node.func) \
                and node.func.attr in self.thread_only \
                and node.func.attr != ROUTER:
            self.sites += 1
            self.findings.append(Finding(
                RULE, self.path, node.lineno, node.col_offset,
                f"handler entry point {self.symbol} calls "
                f"@engine_thread_only method {node.func.attr} directly "
                "(route it through _run_on_engine_thread)",
                symbol=self.symbol))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)


def _foreign_scan(unit, vocab: Vocabulary, owner_spans: List[Tuple[int, int]],
                  findings: List[Finding]) -> int:
    """Flag `<obj>.<engine attr>` on non-self objects anywhere outside
    the owning class bodies, unless under `with <obj>.<lock>:` for the
    attr's declared lock."""
    sites = 0

    def in_owner(line: int) -> bool:
        return any(a <= line <= b for a, b in owner_spans)

    def visit(node: ast.AST, held: Dict[str, Set[str]]) -> None:
        nonlocal sites
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and held:
            # closures do not inherit their definition site's locks
            body = (node.body if isinstance(node.body, list)
                    else [node.body])
            for stmt in body:
                visit(stmt, {})
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = {k: set(v) for k, v in held.items()}
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Attribute):
                    base = expr_key(ce.value)
                    new.setdefault(base, set()).add(ce.attr)
                visit(ce, held)
            for stmt in node.body:
                visit(stmt, new)
            return
        if isinstance(node, ast.Attribute) \
                and node.attr in vocab.engine_attrs \
                and not (isinstance(node.value, ast.Name)
                         and node.value.id == "self") \
                and not in_owner(node.lineno):
            sites += 1
            lock = vocab.engine_attrs[node.attr]
            base = expr_key(node.value)
            if lock is None or lock not in held.get(base, ()):
                want = (f"`with <obj>.{lock}:`" if lock
                        else "engine-thread only; no lock grants access")
                findings.append(Finding(
                    RULE, unit.path, node.lineno, node.col_offset,
                    f".{node.attr} is engine-thread state of another "
                    f"object reached outside its owner ({want})"))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(unit.tree, {})
    return sites


def check(vocab: Vocabulary, units) -> Tuple[List[Finding], int]:
    findings: List[Finding] = []
    sites = 0
    owners = {(c.path, c.name): c for c in vocab.classes
              if c.engine_attrs or c.thread_only_methods
              or c.handler_methods}
    if not owners:
        return findings, sites
    for unit in units:
        spans: List[Tuple[int, int]] = []
        for node in ast.walk(unit.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            decl = owners.get((unit.path, node.name))
            if decl is None:
                continue
            spans.append((node.lineno,
                          getattr(node, "end_lineno", node.lineno)))
            handler: Optional[Set[str]] = set(decl.handler_methods)
            for fn in node.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and fn.name in handler:
                    w = _HandlerWalker(unit.path,
                                       func_symbol(node.name, fn.name),
                                       decl, vocab, findings)
                    w.run(fn)
                    sites += w.sites
        sites += _foreign_scan(unit, vocab, spans, findings)
    return findings, sites
