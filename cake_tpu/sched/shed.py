"""Per-class load shedding with an honest computed Retry-After.

The engine's only overload response used to be a binary queue-full
error. The shed controller replaces that with graceful degradation:

  * the *service rate* is measured from request retirements over a
    sliding window (the same signal PR 3's flight recorder exposes per
    step, aggregated to requests/s);
  * a new arrival's *estimated queue wait* is ``depth_ahead / rate``;
  * while the estimate is inside the class's ``target_wait_s`` SLO the
    request admits with probability 1; beyond it, admission probability
    falls as ``target / est_wait`` — interactive traffic (tight target)
    sheds first and hardest, batch (loose target) keeps queuing;
  * a shed request carries ``retry_after_s = est_wait - target``: the
    time the backlog needs to drain back inside the SLO at the measured
    rate — the API surfaces it as HTTP 429 + ``Retry-After`` instead of
    a generic queue-full error.

Cold start is honest too: with no measured completions yet there is no
basis to refuse, so everything admits (the queue-full bound still
backstops).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from cake_tpu.sched.classes import SchedConfig, validate_priority


class ShedError(Exception):
    """Request rejected by load shedding (HTTP 429). retry_after is the
    computed seconds until the class's backlog drains inside its SLO."""

    def __init__(self, priority: str = "standard",
                 retry_after: float = 1.0,
                 est_wait_s: Optional[float] = None):
        super().__init__(
            f"request shed: estimated {priority!r} queue wait "
            + (f"{est_wait_s:.1f}s " if est_wait_s is not None else "")
            + f"exceeds the class SLO (retry in {retry_after:.0f}s)")
        self.priority = priority
        self.retry_after = retry_after
        self.est_wait_s = est_wait_s


@dataclass(frozen=True)
class ShedDecision:
    admit: bool
    retry_after_s: float
    probability: float
    est_wait_s: Optional[float]


class ShedController:
    """Admission-probability controller fed by retirement timestamps.

    rng/clock are injectable so tests (and multi-process determinism
    experiments) can drive the decision deterministically.
    """

    def __init__(self, config: Optional[SchedConfig] = None,
                 rng: Optional[random.Random] = None, clock=None):
        self.config = config or SchedConfig()
        self._rng = rng or random.Random(0x5ED)
        self._clock = clock or time.monotonic
        self._mu = threading.Lock()
        self._retires: deque = deque(maxlen=512)

    def observe_retire(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._mu:
            self._retires.append(now)

    def service_rate(self, now: Optional[float] = None
                     ) -> Optional[float]:
        """Measured completions/s over the sliding window, or None when
        there is not yet enough signal to estimate."""
        now = self._clock() if now is None else now
        window = self.config.shed_window_s
        with self._mu:
            xs = [t for t in self._retires if now - t <= window]
        if len(xs) < 2:
            return None
        span = max(now - xs[0], 1e-6)
        return len(xs) / span

    def decide(self, priority: str, depth_ahead: int,
               now: Optional[float] = None) -> ShedDecision:
        cls = validate_priority(priority)
        now = self._clock() if now is None else now
        target = self.config.target_wait_s(cls)
        rate = self.service_rate(now)
        if rate is None or rate <= 0.0:
            # no measured signal: admitting is the only honest choice
            return ShedDecision(True, 1.0, 1.0, None)
        est = depth_ahead / rate
        if est <= target:
            return ShedDecision(True, 1.0, 1.0, est)
        p = max(0.0, min(1.0, target / est))
        retry = max(1.0, est - target)
        return ShedDecision(self._rng.random() < p, retry, p, est)

    def estimate_retry_after(self, priority: str, depth_ahead: int,
                             now: Optional[float] = None) -> float:
        """Retry-After for a hard queue-full rejection: same backlog
        math as decide(), with a 1s floor when the rate is unknown."""
        d = self.decide(validate_priority(priority), depth_ahead, now)
        return d.retry_after_s if d.est_wait_s is not None else 1.0
