"""SLO-aware scheduling for the serving engine (`cake_tpu/sched`).

The subsystem that turns the engine from a batcher into a multi-tenant
server. It wraps the existing ``make_scheduler`` seam — the priority-
free native/Python FIFO scheduler (``cake_tpu/native/scheduler.py``)
stays the fallback — with three capabilities:

  1. **Priority classes** (``classes.py``): ``interactive`` /
     ``standard`` / ``batch`` queues with weighted anti-starvation
     aging, so ``plan()`` admits by class, not arrival order.
  2. **Recompute-style preemption** (``slo.py`` victim selection +
     the engine's fold): when a higher class is slot- or page-starved,
     the youngest lowest-class decoding slot is preempted — its
     generated tokens fold into its prompt (the checkpoint-resume
     fold), its pages release through the refcounted allocator, and it
     requeues to re-prefill later, with a per-request preemption budget
     guaranteeing progress.
  3. **Load shedding** (``shed.py``): per-class admission probability
     from measured service rate and queue depth, surfaced as HTTP 429
     with an honest computed ``Retry-After``.
"""

from __future__ import annotations

from typing import Optional

from cake_tpu.sched.classes import (  # noqa: F401
    CLASS_RANK, DEFAULT_PRIORITY, PRIORITY_CLASSES, ROW_KINDS,
    ClassPolicy, SchedConfig, partition_rows, validate_priority,
)
from cake_tpu.sched.shed import (  # noqa: F401
    ShedController, ShedDecision, ShedError,
)
from cake_tpu.sched.slo import SLOScheduler  # noqa: F401


def make_scheduler(max_slots: int, max_queue: int = 1024, *,
                   priority_classes: bool = False,
                   config: Optional[SchedConfig] = None):
    """The scheduler seam: the SLO scheduler when priority classes are
    on, else the native (C++)/Python FIFO fallback unchanged."""
    if priority_classes:
        return SLOScheduler(max_slots, max_queue, config=config)
    from cake_tpu.native.scheduler import make_scheduler as _fifo
    return _fifo(max_slots, max_queue)
