"""SLO-aware scheduler: priority-class queues, aging, preemption.

Drop-in superset of the ``native/scheduler.py`` interface the engine
already drives (submit / cancel / plan / report / queue_depth / active /
completed), plus the operations the SLO layer needs:

  * ``submit(..., priority=)`` — requests carry a class;
  * ``plan()`` admits by *effective score* (class rank minus weighted
    wait-time aging), not arrival order — a fresh ``interactive``
    request leapfrogs a queue of ``batch`` work, but any aged head's
    score falls without bound, so it can never be starved;
  * ``requeue(rid, ...)`` — return an ACTIVE request to the queue
    preserving its original enqueue time (page-starvation requeues and
    recompute-style preemption both must not lose seniority; a plain
    cancel+submit would);
  * ``preemption_victims(below_rank)`` / ``slot_preemption_victims()``
    — candidate decoding slots a starved higher class may reclaim: the
    youngest slot of the worst class, preemption budget respected.

The scheduler is pure host-side bookkeeping (no device work, one lock),
so the property test in tests/test_sched.py can drive hundreds of
random interleavings per millisecond.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from cake_tpu.sched.classes import SchedConfig, validate_priority


class SLOScheduler:
    """Priority-class continuous-batching scheduler (cake_tpu/sched)."""

    def __init__(self, max_slots: int, max_queue: int = 1024,
                 config: Optional[SchedConfig] = None):
        if max_slots <= 0:
            raise ValueError("max_slots must be positive")
        self.max_slots = max_slots
        self.max_queue = max_queue
        self.config = config or SchedConfig()
        self._mu = threading.Lock()
        self._reqs: Dict[int, dict] = {}
        self._queued: List[int] = []
        self._slots: List[int] = [0] * max_slots
        self._active = 0
        self._completed = 0
        self._seq = 0

    # -- internals (caller holds the lock) --------------------------------

    def _score(self, e: dict, now: float) -> float:
        """Effective admission score: lower admits first. The aging
        term guarantees every queued request's score is unbounded
        below — nothing starves."""
        return e["rank"] - max(0.0, now - e["enq_t"]) / e["aging_s"]

    def _order(self, now: float) -> List[int]:
        return sorted(
            self._queued,
            key=lambda r: (self._score(self._reqs[r], now),
                           self._reqs[r]["seq"]))

    # -- the native-scheduler interface -----------------------------------

    def submit(self, rid: int, prompt_len: int, max_new_tokens: int,
               priority: Optional[str] = None,
               now: Optional[float] = None) -> bool:
        cls = validate_priority(priority)
        now = time.monotonic() if now is None else now
        with self._mu:
            if rid == 0 or rid in self._reqs:
                return False
            if len(self._queued) >= self.max_queue:
                return False
            self._seq += 1
            self._reqs[rid] = dict(
                prompt_len=prompt_len, max_new=max_new_tokens,
                generated=0, slot=-1, prefilled=False, cls=cls,
                rank=self.config.rank(cls),
                aging_s=self.config.aging_s(cls),
                enq_t=now, seq=self._seq, preempts=0)
            self._queued.append(rid)
            return True

    def cancel(self, rid: int) -> bool:
        with self._mu:
            e = self._reqs.pop(rid, None)
            if e is None:
                return False
            if e["slot"] >= 0:
                self._slots[e["slot"]] = 0
                self._active -= 1
            else:
                try:
                    self._queued.remove(rid)
                except ValueError:
                    pass
            return True

    def plan(self, now: Optional[float] = None
             ) -> Tuple[List[Tuple[int, int]], List[Tuple[int, int]]]:
        now = time.monotonic() if now is None else now
        with self._mu:
            prefill: List[Tuple[int, int]] = []
            decode: List[Tuple[int, int]] = []
            if self._queued:
                free = [s for s in range(self.max_slots)
                        if self._slots[s] == 0]
                for rid in self._order(now)[:len(free)]:
                    slot = free.pop(0)
                    e = self._reqs[rid]
                    e["slot"] = slot
                    self._slots[slot] = rid
                    self._active += 1
                    self._queued.remove(rid)
                    prefill.append((rid, slot))
            for slot in range(self.max_slots):
                rid = self._slots[slot]
                if rid == 0:
                    continue
                e = self._reqs[rid]
                if e["prefilled"]:
                    decode.append((rid, slot))
                e["prefilled"] = True
            return prefill, decode

    def report(self, rid: int, n_tokens: int, eos: bool) -> bool:
        with self._mu:
            e = self._reqs.get(rid)
            if e is None or e["slot"] < 0:
                return False
            e["generated"] += n_tokens
            if eos or e["generated"] >= e["max_new"]:
                self._slots[e["slot"]] = 0
                self._active -= 1
                self._completed += 1
                del self._reqs[rid]
                return True
            return False

    # -- SLO extensions ----------------------------------------------------

    def resize(self, max_slots: int) -> None:
        """Change the decode-slot count in place — the live engine
        reconfiguration seam (serve/engine.reconfigure): every active
        request must already be requeued (seniority-preserving), so
        only the free-slot map changes. Queued entries, enqueue times
        and preemption counts are untouched."""
        if max_slots <= 0:
            raise ValueError("max_slots must be positive")
        with self._mu:
            if self._active:
                raise RuntimeError(
                    f"resize with {self._active} active request(s): "
                    "requeue them first (reconfigure does)")
            self.max_slots = max_slots
            self._slots = [0] * max_slots

    def requeue(self, rid: int, prompt_len: int, max_new_tokens: int,
                preempted: bool = False) -> bool:
        """Move an ACTIVE request back to its class queue, preserving
        its original enqueue time (seniority survives page-starvation
        requeues and preemption). prompt_len/max_new describe the
        request as it will RE-prefill (generated tokens folded into the
        prompt, budget reduced to the remainder)."""
        with self._mu:
            e = self._reqs.get(rid)
            if e is None or e["slot"] < 0:
                return False
            self._slots[e["slot"]] = 0
            self._active -= 1
            e.update(slot=-1, prefilled=False, prompt_len=prompt_len,
                     max_new=max_new_tokens, generated=0)
            if preempted:
                e["preempts"] += 1
            self._queued.append(rid)
            return True

    def preemption_victims(self, below_rank: int
                           ) -> List[Tuple[int, int]]:
        """(rid, slot) of active requests a class of rank `below_rank`
        may preempt, best victim first: strictly worse class only, the
        worst class first, youngest admission first, requests past
        their preemption budget exempt (progress guarantee)."""
        with self._mu:
            cands = [(e["rank"], e["seq"], rid, e["slot"])
                     for rid, e in self._reqs.items()
                     if e["slot"] >= 0 and e["rank"] > below_rank
                     and e["preempts"] < self.config.preempt_budget]
        cands.sort(key=lambda t: (-t[0], -t[1]))
        return [(rid, slot) for _r, _s, rid, slot in cands]

    def slot_preemption_victims(self, now: Optional[float] = None
                                ) -> List[Tuple[int, int]]:
        """Victims for the best-scored WAITING request when every slot
        is taken; empty when a slot is free or nothing waits."""
        now = time.monotonic() if now is None else now
        with self._mu:
            if not self._queued or any(s == 0 for s in self._slots):
                return []
            best = min(self._queued,
                       key=lambda r: (self._score(self._reqs[r], now),
                                      self._reqs[r]["seq"]))
            rank = self._reqs[best]["rank"]
        return self.preemption_victims(rank)

    def outranks(self, rid_a: int, rid_b: int,
                 now: Optional[float] = None) -> bool:
        """True when rid_a's effective score strictly beats rid_b's —
        the page-starved blocking head may only be leapfrogged by a
        request that outranks it, so an aged head keeps first claim on
        freed pages."""
        now = time.monotonic() if now is None else now
        with self._mu:
            ea, eb = self._reqs.get(rid_a), self._reqs.get(rid_b)
            if ea is None or eb is None:
                return False
            return ((self._score(ea, now), ea["seq"])
                    < (self._score(eb, now), eb["seq"]))

    def queue_pressure(self, now: Optional[float] = None) -> float:
        """Dimensionless admission pressure for the autotune signal
        gather (AutotuneSignals.queue_pressure): the maximum aging a
        queued request has accumulated, in rank steps — 0.0 when the
        queue is empty, 1.0 when some request has waited one full
        aging_s, climbing without bound as the backlog ages. Unlike
        raw depth, this is comparable across classes (a batch request
        ages 2x slower than an interactive one by default) and rises
        exactly when the anti-starvation machinery is working hardest
        — the signal offered rps alone cannot see."""
        now = time.monotonic() if now is None else now
        with self._mu:
            if not self._queued:
                return 0.0
            return max(
                max(0.0, now - self._reqs[r]["enq_t"])
                / self._reqs[r]["aging_s"]
                for r in self._queued)

    def class_depths(self) -> Dict[str, int]:
        """Queued requests per class (the cake_queue_depth gauge)."""
        out = {p.name: 0 for p in self.config.policies}
        with self._mu:
            for rid in self._queued:
                out[self._reqs[rid]["cls"]] += 1
        return out

    def depth_ahead(self, priority: str) -> int:
        """Approximate queue positions ahead of a NEW request of this
        class: queued requests of the same or better rank (aging can
        promote worse classes past this estimate; shedding only needs
        the order of magnitude)."""
        rank = self.config.rank(validate_priority(priority))
        with self._mu:
            return sum(1 for rid in self._queued
                       if self._reqs[rid]["rank"] <= rank)

    def preempt_count(self, rid: int) -> int:
        with self._mu:
            e = self._reqs.get(rid)
            return 0 if e is None else e["preempts"]

    # -- properties --------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._mu:
            return len(self._queued)

    @property
    def active(self) -> int:
        with self._mu:
            return self._active

    @property
    def completed(self) -> int:
        with self._mu:
            return self._completed
