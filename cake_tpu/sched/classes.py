"""Priority classes and scheduling policy knobs for `cake_tpu/sched`.

Three classes order admission at "millions of users" scale, where FIFO
is the wrong policy (one batch prompt head-of-line-blocks every
interactive request):

  * ``interactive`` — latency-sensitive chat turns (best rank);
  * ``standard``    — the default for unmarked traffic;
  * ``batch``       — offline/bulk work (worst rank, cheapest to shed).

A request's admission order is its *effective score*
``rank - wait / aging_s``: lower is better, and the aging term is the
anti-starvation guarantee — any queued request's score falls without
bound as it waits, so an aged batch head eventually outranks a fresh
interactive arrival and MUST be admitted next (property-tested in
tests/test_sched.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

PRIORITY_CLASSES: Tuple[str, ...] = ("interactive", "standard", "batch")
CLASS_RANK: Dict[str, int] = {c: i for i, c in enumerate(PRIORITY_CLASSES)}
DEFAULT_PRIORITY = "standard"

# The row KINDS one engine iteration's plan can put on the device,
# orthogonal to the priority classes above: every kind is admitted
# under the same class policy (spec rows are ordinary decode rows to
# the scheduler — only the engine's per-row partition decides whether
# a decode row rides a speculative round this iteration). Step records
# (cake_tpu/obs/steps.py) and the spec plane use this vocabulary.
ROW_KINDS: Tuple[str, ...] = ("prefill", "decode", "spec")


def partition_rows(plan, predicate):
    """Split a plan's ``(rid, slot)`` rows by ``predicate(rid, slot)``
    into (matching, rest), both order-preserving — the engine's row-
    kind split (e.g. which decode rows ride this iteration's
    speculative round) without re-ranking anything the scheduler
    already ordered."""
    hit, rest = [], []
    for rid, slot in plan:
        (hit if predicate(rid, slot) else rest).append((rid, slot))
    return hit, rest


def validate_priority(priority: Optional[str]) -> str:
    """Normalize a request priority: None -> the default class; an
    unknown value raises ValueError (the API maps it to HTTP 400)."""
    if priority is None:
        return DEFAULT_PRIORITY
    if priority not in CLASS_RANK:
        raise ValueError(
            f"unknown priority {priority!r} (choose one of "
            f"{', '.join(PRIORITY_CLASSES)})")
    return priority


@dataclass(frozen=True)
class ClassPolicy:
    """One priority class's scheduling knobs.

    aging_s: seconds of queue wait that cancel ONE rank step of
    disadvantage (the weighted anti-starvation aging term).
    target_wait_s: the class's SLO on estimated queue wait — load
    shedding starts rejecting probabilistically beyond it (shed.py).
    """

    name: str
    rank: int
    aging_s: float
    target_wait_s: float


DEFAULT_POLICIES: Tuple[ClassPolicy, ...] = (
    ClassPolicy("interactive", 0, aging_s=30.0, target_wait_s=2.0),
    ClassPolicy("standard", 1, aging_s=30.0, target_wait_s=15.0),
    ClassPolicy("batch", 2, aging_s=60.0, target_wait_s=120.0),
)


@dataclass(frozen=True)
class SchedConfig:
    """Policy bundle consumed by SLOScheduler and ShedController.

    preempt_budget: times one request may be preempted before it
    becomes exempt (guarantees forward progress for low classes).
    shed_window_s: sliding window over which the shed controller
    measures the engine's service rate.
    """

    policies: Tuple[ClassPolicy, ...] = DEFAULT_POLICIES
    preempt_budget: int = 2
    shed_window_s: float = 30.0
    # prefer SPILLING a preemption victim's KV pages to the host tier
    # (when --kv-host-pages capacity is free) over the recompute fold:
    # resume then restores pages instead of re-prefilling prompt +
    # generated tokens (cake_tpu/kv/host_tier.py). False forces the
    # PR-5 recompute-resume path even with a host tier configured.
    spill_preempt: bool = True
    # oversubscribe the KV pool: an admission the pool cannot cover
    # (even after cold-prefix spills) may park decode-RESIDENT streams
    # — LRU by admission — in the host tier instead of waiting for
    # natural retirements (serve/engine._spill_resident_stream). False
    # restricts host-tier spills to cold prefixes + preemption victims.
    spill_resident: bool = True
    # anti-thrash quantum for the resident spill: a stream may not be
    # parked until it has decoded this many tokens since its latest
    # admission, so two oversubscribed streams time-slice the pool in
    # quantum-sized turns instead of ping-ponging one token per park
    # (each park costs two host round trips).
    resident_quantum: int = 8

    def policy(self, name: str) -> ClassPolicy:
        for p in self.policies:
            if p.name == name:
                return p
        raise ValueError(f"no policy for class {name!r}")

    def rank(self, name: str) -> int:
        return self.policy(name).rank

    def aging_s(self, name: str) -> float:
        return self.policy(name).aging_s

    def target_wait_s(self, name: str) -> float:
        return self.policy(name).target_wait_s
