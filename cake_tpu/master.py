"""Master orchestration: model loading + generation driving.

Capability parity with the reference `Master` (cake-core/src/cake/master.rs):
`generate_text` streams each token through a callback, re-times from token 1
so the compile/warmup token doesn't skew throughput, and logs tokens/s
(master.rs:80-124); `generate_image` delegates to the image generator
(master.rs:126-132); `reset()` clears chat state (master.rs:75-77).

There is no worker process: the "cluster" is the device mesh, and model
assembly is sharding (parallel/), so Master is a thin driver over a
Generator.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

import jax.numpy as jnp

from cake_tpu.args import Args
from cake_tpu.models import Token
from cake_tpu.models.chat import Message
from cake_tpu.ops.sampling import SamplingConfig

log = logging.getLogger(__name__)


class Master:
    """Drives a text and/or image generator (reference master.rs:12-133)."""

    def __init__(self, args: Args, text_generator=None, image_generator=None):
        self.args = args
        self.llm = text_generator
        self.image = image_generator
        self.tokens_per_s: float = 0.0

    # -- construction --------------------------------------------------------

    @classmethod
    def from_args(cls, args: Args, sd_args=None) -> "Master":
        from cake_tpu.context import Context
        ctx = Context.from_args(args, sd_args)
        if args.model_type.value == "image":
            return cls(args, image_generator=ctx.load_image_model())
        return cls(args, text_generator=ctx.load_text_model())

    def make_engine(self, max_slots: Optional[int] = None,
                    **engine_kwargs):
        """Build a continuous-batching engine sharing the loaded LLM's
        params (no weight copy; the engine allocates its own batched KV
        cache). Used by the REST server so N requests decode together
        instead of serialising on a lock like the reference (api/text.rs:67).
        engine_kwargs pass through to InferenceEngine on every flavor
        (e.g. recovery_config for crash-recovery tuning).
        """
        if self.llm is None:
            raise RuntimeError("no text generator loaded")
        from cake_tpu.serve import InferenceEngine
        g = self.llm
        from cake_tpu.models.llama.speculative import SpeculativeGenerator
        if isinstance(g, SpeculativeGenerator):
            import jax
            if jax.process_count() > 1:
                # the spec engine's batched rounds are single-device;
                # no multi-host step replay exists for them
                log.info("no multi-host engine for --draft-model")
                return None
            # round-5: speculation inside the batching engine — the
            # draft/verify rounds run BATCHED across slots (spec_round_batched), so
            # concurrent API requests all speculate, stream, and
            # checkpoint like any other engine request
            if getattr(self.args, "kv_dtype", None) in ("int8", "int4"):
                # loud config error, not a warning: an operator asking
                # for quantized KV expects the capacity win, and the
                # spec engine (gated off the paged pool) cannot
                # deliver it
                from cake_tpu.args import INT8_KV_SPEC_ERROR
                raise ValueError(INT8_KV_SPEC_ERROR)
            if getattr(self.args, "kv_pages", None):
                log.warning("--kv-pages ignored with --draft-model: the "
                            "spec engine's target+draft caches are not "
                            "paged")
            if getattr(self.args, "kv_host_pages", None):
                log.warning("--kv-host-pages ignored with --draft-model:"
                            " the host KV tier spills paged pool pages")
            if getattr(self.args, "auto_prefix", False):
                log.warning("--auto-prefix ignored with --draft-model: "
                            "prefix caching is not implemented for the "
                            "spec engine (draft cache has no prefix "
                            "install path)")
            if getattr(self.args, "mixed_batch", "auto") == "on":
                log.warning("--mixed-batch ignored with --draft-model: "
                            "the mixed ragged step is a paged-engine "
                            "path and the spec engine is not paged")
            if getattr(self.args, "autotune", "off") != "off":
                log.warning("--autotune ignored with --draft-model: "
                            "speculative serving has no hot-switch "
                            "fold (the draft cache cannot be rebuilt "
                            "mid-round)")
            slots = max_slots or getattr(self.args, "max_slots", 8)
            return InferenceEngine(
                g.config, g.params, g.tokenizer,
                max_slots=slots,
                max_seq_len=g.max_seq_len,
                sampling=g.sampling,
                seed=self.args.seed,
                cache_dtype=g.cache.k.dtype,
                draft_params=g.draft_params,
                draft_config=g.draft_config,
                spec_gamma=g.gamma,
                **self._trace_kwargs(),
                **self._sched_kwargs(),
                **self._fault_kwargs(),
                # passed through so the engine's own guard WARNS that
                # multi-step scans don't apply in speculative mode
                # (each round already advances up to gamma+1 tokens),
                # instead of the flag silently vanishing
                decode_scan_steps=self.args.decode_scan,
                **engine_kwargs,
            )
        fwd = getattr(g, "_forward_fn", None)
        if fwd is not None and g.parallel is None:
            # custom forward without a (plan, mesh): the --sp adapter.
            # Round-5: plain sp and sp x tp get a REAL engine contract
            # (ring slot-prefill + merged-stats ragged decode,
            # context_parallel.make_sp_engine_step_fns) — long-context
            # serving batches concurrent requests instead of serialising
            # on the legacy locked path. dp x sp shards slots over dp;
            # no text serving mode locks anymore.
            slots = max_slots or getattr(self.args, "max_slots", 8)
            pieces = None
            engine_pieces = getattr(fwd, "engine_pieces", None)
            if engine_pieces is not None:
                pieces = engine_pieces(slots, g.params)
            if pieces is None:
                log.info("no batching engine for this serving mode: "
                         "the API serves requests one at a time through "
                         "the generator")
                return None
            fns, cache, ctx_len, tail_len = pieces
            if getattr(self.args, "kv_pages", None):
                log.warning("--kv-pages ignored: the sp engine's "
                            "ctx/tail cache is not paged (the ctx "
                            "region is sequence-sharded, not "
                            "slot-paged)")
            if (getattr(self.args, "kv_dtype", None) in ("int8", "int4")
                    or getattr(self.args, "kv_host_pages", None)):
                log.warning("--kv-dtype int8/int4 / --kv-host-pages "
                            "ignored: KV tiering (cake_tpu/kv) applies "
                            "to the paged pool, and the sp engine's "
                            "ctx/tail cache is not paged")
            if getattr(self.args, "auto_prefix", False):
                log.warning("--auto-prefix ignored: prefix caching is "
                            "not implemented for the sp engine's "
                            "sequence-sharded ctx cache")
            if getattr(self.args, "mixed_batch", "auto") == "on":
                log.warning("--mixed-batch ignored: the sp engine's "
                            "ctx/tail cache is not paged, so there is "
                            "no mixed ragged step to dispatch")
            if getattr(self.args, "autotune", "off") != "off":
                log.warning("--autotune ignored: the sp engine's "
                            "custom step fns own their cache contract; "
                            "only the built-in dense/paged engines can "
                            "hot-switch configs")
            if getattr(self.args, "disagg", None):
                log.warning("--disagg ignored: disaggregated "
                            "prefill/decode ships paged pool pages "
                            "(cake_tpu/kv/transfer.py), and the sp "
                            "engine's ctx/tail cache is not paged")
            log.info("sp engine: %d slots, ctx window %d + decode tail "
                     "%d", slots, ctx_len, tail_len)
            return InferenceEngine(
                g.config, g.params, g.tokenizer,
                max_slots=slots, max_seq_len=ctx_len + tail_len,
                sampling=g.sampling, seed=self.args.seed,
                decode_scan_steps=self.args.decode_scan,
                step_fns=fns, cache=cache,
                prompt_limit=ctx_len, decode_budget=tail_len,
                **self._trace_kwargs(),
                **self._sched_kwargs(),
                **self._fault_kwargs(),
                # passed through so the engine's no-chunk-fn guard WARNS
                # that --prefill-chunk has no sp variant, instead of the
                # flag silently vanishing
                prefill_chunk=getattr(self.args, "prefill_chunk", None),
                **engine_kwargs,
            )
        slots = max_slots or getattr(self.args, "max_slots", 8)
        kwargs = {}
        if getattr(g, "parallel", None) is not None:
            # topology-sharded model: the engine's steps run the same
            # pipelined SPMD program, with its batched cache placed to match
            from cake_tpu.parallel.pipeline import make_engine_step_fns
            from cake_tpu.parallel.sharding import create_sharded_cache
            plan, mesh = g.parallel
            tp = plan.tp > 1
            microbatches = self.args.microbatches
            if slots % microbatches != 0:
                raise ValueError(
                    f"--max-slots {slots} must be divisible by "
                    f"--microbatches {microbatches}")
            # sliding-window model over a topology: ring cache per stage
            # (W slots instead of max_seq), same memory win as the
            # single-device engine's ring path
            ring = (g.config.sliding_window is not None
                    and g.config.sliding_window < g.max_seq_len)
            cache = create_sharded_cache(
                g.config, slots,
                g.config.sliding_window if ring else g.max_seq_len, mesh,
                tp_axis="tp" if tp else None, dp_axis=None,
                stage_axis="stage", dtype=g.cache.k.dtype,
            )
            kwargs = dict(
                step_fns=make_engine_step_fns(
                    mesh, g.config, num_microbatches=microbatches,
                    tp=tp, params=g.params, ring=ring),
                cache=cache,
                ring=ring,
            )
        return InferenceEngine(
            g.config, g.params, g.tokenizer,
            max_slots=slots,
            max_seq_len=g.max_seq_len,
            sampling=g.sampling,
            seed=self.args.seed,
            decode_scan_steps=self.args.decode_scan,
            cache_dtype=g.cache.k.dtype,  # follow --kv-dtype
            # honored by the paged (--kv-pages) engine too: prefixes
            # prefill once into pool pages and map shared, and chunked
            # prefill windows scatter into pages at any offset
            auto_prefix_system=getattr(self.args, "auto_prefix", False),
            # pass through unconditionally: the engine's own step_fns
            # guard warns when a pipelined path ignores the knob
            prefill_chunk=getattr(self.args, "prefill_chunk", None),
            kv_pages=getattr(self.args, "kv_pages", None),
            kv_page_size=getattr(self.args, "kv_page_size", 128),
            paged_attn=getattr(self.args, "paged_attn", "auto"),
            # KV tiering (cake_tpu/kv): "int8" selects the quantized
            # page pool; --kv-host-pages arms the host-RAM spill tier
            # (both are paged-pool features — the engine warns/errors
            # when --kv-pages is absent)
            kv_dtype=getattr(self.args, "kv_dtype", None),
            kv_host_pages=getattr(self.args, "kv_host_pages", None),
            # token-level continuous batching: the paged engine's mixed
            # ragged step (auto = on for --kv-pages serving; "on"
            # without --kv-pages is rejected by the engine with a
            # named reason instead of silently vanishing)
            mixed_batch=getattr(self.args, "mixed_batch", "auto"),
            # live config hot-switching (cake_tpu/autotune): the
            # engine itself warns and disables on flavors without the
            # fold (ring/custom step fns)
            autotune=getattr(self.args, "autotune", "off"),
            autotune_policy=getattr(self.args, "autotune_policy", None),
            # disaggregated prefill/decode (cake_tpu/kv/transfer.py):
            # role + channel peer; the shared token rides
            # $CAKE_DISAGG_TOKEN (validated loudly at startup)
            disagg=getattr(self.args, "disagg", None),
            disagg_peer=getattr(self.args, "disagg_peer", None),
            disagg_timeout_s=getattr(self.args, "disagg_timeout", 30.0),
            **self._spec_kwargs(),
            **self._trace_kwargs(),
            **self._sched_kwargs(),
            **self._fault_kwargs(),
            **kwargs,
            **engine_kwargs,
        )

    def _spec_kwargs(self) -> dict:
        """Paged speculative decoding (cake_tpu/spec): load the draft
        model behind --spec-draft and hand the engine its params +
        config (the engine builds the paged draft pool itself, sized by
        the target pool's page geometry). Config resolution mirrors
        context._load_speculative; the draft stays unquantized
        (--quant targets the big model — a paged draft is small by
        construction)."""
        d_dir = getattr(self.args, "spec_draft", None)
        if not d_dir:
            return {}
        import dataclasses
        import os

        from cake_tpu.context import _resolve_flash
        from cake_tpu.models import load_text_params
        from cake_tpu.models.llama.config import LlamaConfig, load_config
        from cake_tpu.utils.devices import resolve_dtype
        g = self.llm
        if os.path.exists(os.path.join(d_dir, "config.json")):
            d_cfg = load_config(d_dir)
        else:
            d_cfg = LlamaConfig.tiny()
        d_cfg = dataclasses.replace(
            d_cfg, use_flash_attention=_resolve_flash(self.args))
        if d_cfg.vocab_size != g.config.vocab_size:
            raise ValueError(
                f"spec draft vocab {d_cfg.vocab_size} != target vocab "
                f"{g.config.vocab_size}: the verify pass scores draft "
                "token ids directly, so the models must share a "
                "tokenizer")
        d_params = load_text_params(d_cfg, d_dir,
                                    resolve_dtype(self.args.dtype))
        log.info("paged speculative serving: gamma=%d draft=%s",
                 self.args.spec_gamma, d_dir)
        return dict(spec_draft_params=d_params,
                    spec_draft_config=d_cfg,
                    spec_gamma=self.args.spec_gamma)

    def _trace_kwargs(self) -> dict:
        """Request-lifecycle tracing + step-telemetry + event-bus +
        SLO-accounting knobs, plumbed to every engine flavor
        identically (--trace-events / --trace-ring / --step-log /
        --step-ring / --event-log / --event-ring / --slo-targets)."""
        return dict(
            trace_events=getattr(self.args, "trace_events", None),
            trace_ring=getattr(self.args, "trace_ring", 256),
            step_log=getattr(self.args, "step_log", None),
            step_ring=getattr(self.args, "step_ring", 512),
            event_log=getattr(self.args, "event_log", None),
            event_ring=getattr(self.args, "event_ring", 1024),
            slo_targets=getattr(self.args, "slo_targets", None),
            # online regression sentinel (--sentinel, obs/sentinel.py)
            sentinel=getattr(self.args, "sentinel", False),
            sentinel_interval=getattr(self.args, "sentinel_interval",
                                      2.0),
            # closed-loop actuation + black-box forensics (ISSUE 16,
            # obs/actions.py): --sentinel-act / --postmortem-dir
            sentinel_act=getattr(self.args, "sentinel_act", False),
            postmortem_dir=getattr(self.args, "postmortem_dir", None),
        )

    def _sched_kwargs(self) -> dict:
        """SLO scheduling knobs (--priority-classes / --preemption /
        --shed), plumbed to every engine flavor; the engine itself
        warns and degrades when a flavor cannot preempt (speculative,
        windowed ctx+tail layouts)."""
        return dict(
            priority_classes=getattr(self.args, "priority_classes",
                                     False),
            preemption=getattr(self.args, "preemption", None),
            shed=getattr(self.args, "shed", False),
        )

    def telemetry_settings(self) -> tuple:
        """(enabled, interval_s) for fleet telemetry federation
        (--telemetry-export / --telemetry-interval, obs/federation.py).
        Resolves the auto default here — ONE place — so the
        coordinator's collector and every follower's exporter agree on
        whether the plane is armed: None = on exactly when serving
        spans processes (followers are otherwise observability black
        holes), an explicit True/False is honored as given."""
        enabled = getattr(self.args, "telemetry_export", None)
        if enabled is None:
            import jax
            enabled = jax.process_count() > 1
        return (bool(enabled),
                float(getattr(self.args, "telemetry_interval", 2.0)))

    def _fault_kwargs(self) -> dict:
        """Fault-injection + crash-recovery + durability knobs
        (--fault-plan / --recovery / --journal / --journal-fsync),
        plumbed to every engine flavor; the engine warns and keeps the
        legacy fail-all path where the resume fold does not exist
        (speculative, windowed ctx+tail layouts — the journal still
        records and replays there, through the same resume path
        checkpoints use)."""
        return dict(
            fault_plan=getattr(self.args, "fault_plan", None),
            recovery=getattr(self.args, "recovery", None),
            journal=getattr(self.args, "journal", None),
            journal_fsync=getattr(self.args, "journal_fsync", "batch"),
        )

    # -- text ----------------------------------------------------------------

    def reset(self) -> None:
        if self.llm is not None:
            self.llm.reset()

    def add_message(self, message: Message) -> None:
        self.llm.add_message(message)

    def generate_text(self, stream: Callable[[Token], None],
                      sample_len: Optional[int] = None) -> str:
        """Generate up to sample_len tokens, streaming each through `stream`.

        Timing matches the reference (master.rs:93-121): the clock restarts
        after the first token so one-off compile cost is excluded from the
        reported tokens/s.
        """
        sample_len = sample_len or self.args.sample_len
        pieces = []
        start = time.perf_counter()
        generated = 0
        for index in range(sample_len):
            token = self.llm.next_token(index)
            if index == 0:
                start = time.perf_counter()  # exclude warmup token
            else:
                generated += 1
            if token.is_end_of_stream:
                if token.text:
                    # EOS carries the flushed UTF-8 tail (generator
                    # parity with the buffered decode)
                    pieces.append(token.text)
                    stream(token)
                break
            pieces.append(token.text)
            stream(token)
        dt = time.perf_counter() - start
        self.tokens_per_s = generated / dt if dt > 0 else 0.0
        log.info("%d tokens generated (%.2f token/s)",
                 generated + 1, self.tokens_per_s)
        return "".join(pieces)

    # -- image ---------------------------------------------------------------

    def attach_image_control(self, control) -> None:
        """Multi-host image serving: publish each generation's args
        before dispatching it, so follower processes replay the
        identical jit sequence (cli._run_image_follower)."""
        self._image_control = control

    def generate_image(self, image_args, callback) -> None:
        if self.image is None:
            raise RuntimeError("no image generator loaded")
        control = getattr(self, "_image_control", None)
        if control is not None:
            if image_args.sd_img2img:
                # the path is coordinator-local; a follower replaying it
                # would fail AFTER its first collectives and desync the
                # SPMD dispatch, wedging the cluster — reject up front
                # with a clean client error instead
                raise ValueError(
                    "img2img is unavailable under multi-host serving: "
                    "the init image exists on the coordinator only; "
                    "serve img2img on one host")
            control.publish({"op": "image", "args": image_args.to_json()})
        self.image.generate_image(image_args, callback)

    def run(self) -> None:
        """One-shot CLI generation (reference master.rs:33-72)."""
        if self.llm is not None:
            self.add_message(Message.system(self.args.system_prompt))
            self.add_message(Message.user(self.args.prompt))
            print(f"[{self.args.system_prompt}] {self.args.prompt}\n")
            self.generate_text(lambda t: print(t.text, end="", flush=True))
            print()
