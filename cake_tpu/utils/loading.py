"""Safetensors weight loading with index resolution.

Capability parity with `load_safetensors_paths_from_index` /
`load_var_builder_from_index` (reference utils/mod.rs:32-104): resolve the
file set from `model.safetensors.index.json`'s weight_map, falling back to a
single `model.safetensors`, then load tensors (mmap'd on the host) into jax
arrays.

TPU additions over the reference:
  * optional name-prefix filtering so a pipeline stage / host only
    materialises the tensors it owns (the reference worker mmaps the full
    index and relies on lazy page mapping, worker.rs:106-127 — here we simply
    never read unneeded tensors);
  * optional per-tensor `jax.sharding.NamedSharding` placement so weights
    land directly on their mesh shard without a full host copy per device.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Iterable, List, Optional

import numpy as np

INDEX_FILE = "model.safetensors.index.json"
SINGLE_FILE = "model.safetensors"


def has_weights(model_dir: Optional[str]) -> bool:
    """True when model_dir holds weights in a layout load_weights reads
    (indexed/single-file directory, or a direct .safetensors path — the
    shape diffusers per-component checkpoints ship in)."""
    if not model_dir:
        return False
    if os.path.isfile(model_dir) and model_dir.endswith(".safetensors"):
        return True
    return (
        os.path.exists(os.path.join(model_dir, SINGLE_FILE))
        or os.path.exists(os.path.join(model_dir, INDEX_FILE))
    )

# safetensors dtype string -> numpy dtype for raw-buffer interpretation.
# bf16 is viewed through ml_dtypes (ships with jax).
import ml_dtypes  # noqa: E402

_ST_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "BF16": ml_dtypes.bfloat16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
    "F8_E4M3": ml_dtypes.float8_e4m3fn,
    "F8_E5M2": ml_dtypes.float8_e5m2,
}


def load_weight_index(model_dir: str) -> Dict[str, str]:
    """tensor name -> safetensors filename.

    Reads `model.safetensors.index.json` weight_map; falls back to mapping
    every tensor of a single `model.safetensors` (utils/mod.rs:42-82).
    """
    if os.path.isfile(model_dir):  # direct .safetensors file
        return {name: os.path.basename(model_dir)
                for name in _st_tensor_names(model_dir)}
    index_path = os.path.join(model_dir, INDEX_FILE)
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        weight_map = index.get("weight_map")
        if not weight_map:
            raise ValueError(f"{index_path} has no weight_map")
        return dict(weight_map)
    single = os.path.join(model_dir, SINGLE_FILE)
    if not os.path.exists(single):
        raise FileNotFoundError(
            f"neither {INDEX_FILE} nor {SINGLE_FILE} found in {model_dir}"
        )
    return {name: SINGLE_FILE for name in _st_tensor_names(single)}


def load_safetensors_paths_from_index(model_dir: str) -> List[str]:
    """Unique safetensors file paths for a model directory."""
    weight_map = load_weight_index(model_dir)
    seen: List[str] = []
    for fname in weight_map.values():
        path = os.path.join(model_dir, fname)
        if path not in seen:
            seen.append(path)
    return seen


def _st_read_header(path: str):
    """Parse a safetensors header: (header_dict, data_offset)."""
    with open(path, "rb") as f:
        n = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(n))
    header.pop("__metadata__", None)
    return header, 8 + n


def _st_tensor_names(path: str) -> List[str]:
    header, _ = _st_read_header(path)
    return list(header.keys())


def _st_load_file(
    path: str,
    names: Optional[Iterable[str]] = None,
) -> Dict[str, np.ndarray]:
    """Load (a subset of) tensors from one safetensors file via mmap.

    Zero-copy views into the mmap where possible; the caller converts to
    device arrays (which copies once, host->device).
    """
    header, data_offset = _st_read_header(path)
    wanted = set(names) if names is not None else None
    mm = np.memmap(path, dtype=np.uint8, mode="r", offset=data_offset)
    out: Dict[str, np.ndarray] = {}
    for name, meta in header.items():
        if wanted is not None and name not in wanted:
            continue
        dtype = _ST_DTYPES[meta["dtype"]]
        shape = meta["shape"]
        begin, end = meta["data_offsets"]
        arr = mm[begin:end].view(dtype)
        out[name] = arr.reshape(shape)
    return out


def load_weights(
    model_dir: str,
    filter_fn: Optional[Callable[[str], bool]] = None,
    to_device: Optional[Callable[[str, np.ndarray], object]] = None,
    prefetch: bool = True,
) -> Dict[str, object]:
    """Load model weights by name.

    filter_fn:  keep only tensors for which filter_fn(name) is True
                (stage-local loading; replaces cake-split-model's offline
                pruning for the common case).
    to_device:  optional (name, host_array) -> device array placement hook;
                defaults to returning the host array untouched so the caller
                controls dtype casting + sharding.
    prefetch:   madvise(WILLNEED) each tensor's pages up front (native
                reader). Pass False when the caller will only touch shard
                slices of each tensor (load_params_sharded) — prefetching
                would fault in the whole checkpoint on every host.
    """
    from cake_tpu.native.safetensors import read_file

    weight_map = load_weight_index(model_dir)
    base_dir = (os.path.dirname(model_dir) if os.path.isfile(model_dir)
                else model_dir)
    by_file: Dict[str, List[str]] = {}
    for name, fname in weight_map.items():
        if filter_fn is not None and not filter_fn(name):
            continue
        by_file.setdefault(fname, []).append(name)
    out: Dict[str, object] = {}
    for fname, names in by_file.items():
        # native mmap reader (madvise-prefetched zero-copy views) when the
        # C++ library built; numpy memmap otherwise. Views keep their
        # mapping alive through the array base chain in both cases.
        tensors, _handle = read_file(os.path.join(base_dir, fname), names,
                                     prefetch=prefetch)
        for name, arr in tensors.items():
            out[name] = to_device(name, arr) if to_device else arr
    return out


def save_safetensors(path: str, tensors: Dict[str, np.ndarray]) -> None:
    """Write a safetensors file (used by tools/split_model.py)."""
    _NP_TO_ST = {np.dtype(v): k for k, v in _ST_DTYPES.items()}
    header = {}
    offset = 0
    blobs = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        blob = arr.tobytes()
        header[name] = {
            "dtype": _NP_TO_ST[arr.dtype],
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(blob)],
        }
        offset += len(blob)
        blobs.append(blob)
    hdr = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(len(hdr).to_bytes(8, "little"))
        f.write(hdr)
        for blob in blobs:
            f.write(blob)
