"""Utilities: device/dtype policy, weight loading, debug helpers.

Capability parity with the reference's `cake-core/src/utils/mod.rs`.
"""

from cake_tpu.utils.devices import get_inference_device, resolve_dtype  # noqa: F401
from cake_tpu.utils.loading import (  # noqa: F401
    load_safetensors_paths_from_index,
    load_weights,
    load_weight_index,
)
from cake_tpu.utils.debug import panic_on_nan  # noqa: F401
from cake_tpu.utils.profiling import (  # noqa: F401
    StepStats, annotate, device_memory_stats, human_bytes, log_memory, trace,
)
