"""Debug helpers (reference: panic_on_nan, utils/mod.rs:106-112)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def panic_on_nan(x, name: str = "tensor"):
    """Raise if any element is NaN; returns x unchanged otherwise.

    The reference stringifies the tensor and greps for "NaN"
    (utils/mod.rs:106-112); here we use a proper reduction, and
    `jax.debug.callback`-free host check (call outside jit, or wrap with
    `checked` below inside jit).
    """
    if bool(jnp.isnan(jnp.asarray(x)).any()):
        raise FloatingPointError(f"NaN detected in {name}")
    return x


def checked(x, name: str = "tensor"):
    """jit-safe NaN check via debug callback (no-op on clean tensors)."""
    def _cb(has_nan):
        if has_nan:
            raise FloatingPointError(f"NaN detected in {name}")
    jax.debug.callback(_cb, jnp.isnan(x).any())
    return x
