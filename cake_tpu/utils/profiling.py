"""Tracing / profiling / memory observability.

Reference surface (SURVEY.md §5):
  * `--sd-tracing` installs a Chrome-trace subscriber writing
    `trace-*.json` (sd/sd.rs:350-356) — here `trace(dir)` wraps
    `jax.profiler.trace`, producing a TensorBoard/Perfetto profile of
    both host Python and on-device XLA execution (strictly more detail
    than the reference's host-side spans), plus `annotate(name)` for
    custom spans (`jax.profiler.TraceAnnotation`).
  * worker ops/s + read/write throughput logged every 5 ops
    (worker.rs:19, 254-283) — here `StepStats`, a windowed counter the
    engine/drivers call per step.
  * memory reporting at context creation / model load / inference start
    (cake/mod.rs:65-71, memory-stats + human_bytes) — here
    `log_memory(tag)` over `Device.memory_stats()` (real HBM numbers on
    TPU, not host RSS).
"""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax

log = logging.getLogger(__name__)

NUM_OPS_TO_STATS = 5  # reference worker.rs:19


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """Profile everything inside the block to `log_dir` (None = no-op).

    View with TensorBoard's profile plugin or upload the generated
    `*.trace.json.gz` (perfetto trace) to ui.perfetto.dev — the TPU-era
    equivalent of the reference's chrome://tracing JSON.
    """
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir, create_perfetto_trace=True):
        log.info("profiling to %s", log_dir)
        yield
    log.info("profile written to %s", log_dir)


def annotate(name: str):
    """Named span visible in the profile (host + device timeline)."""
    return jax.profiler.TraceAnnotation(name)


def capture_trace(seconds: float, out_dir: Optional[str] = None) -> dict:
    """Capture a jax.profiler trace of the NEXT `seconds` of live
    execution (the POST /api/v1/profile backend, obs/steps.py): unlike
    `trace(dir)` — which wraps a code block the caller controls — this
    profiles whatever the process is doing right now (a serving engine
    mid-decode), then returns where the artifacts landed.

    out_dir: capture directory (created if missing); None makes a fresh
    temp dir per capture. Returns {"dir", "perfetto_trace", "seconds"}
    where perfetto_trace is the newest ``*.trace.json.gz`` under dir
    (upload to ui.perfetto.dev), or None if the backend produced only
    the TensorBoard artifacts."""
    import os
    import tempfile

    d = out_dir or tempfile.mkdtemp(prefix="cake-profile-")
    os.makedirs(d, exist_ok=True)
    t0 = time.perf_counter()
    jax.profiler.start_trace(d, create_perfetto_trace=True)
    try:
        time.sleep(seconds)
    finally:
        jax.profiler.stop_trace()
    captured = time.perf_counter() - t0
    newest, newest_mtime = None, -1.0
    for root, _dirs, files in os.walk(d):
        for name in files:
            if name.endswith(".trace.json.gz"):
                p = os.path.join(root, name)
                try:
                    m = os.path.getmtime(p)
                except OSError:
                    continue
                if m > newest_mtime:
                    newest, newest_mtime = p, m
    log.info("profiler capture: %.2fs -> %s", captured, newest or d)
    return {"dir": d, "perfetto_trace": newest,
            "seconds": round(captured, 3)}


def human_bytes(n: float) -> str:
    """1536 -> '1.5 KiB' (reference human_bytes crate semantics)."""
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def device_memory_stats() -> List[Dict[str, object]]:
    """Per-device memory usage. Empty fields on backends without stats."""
    out = []
    for d in jax.local_devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — CPU backend has no stats
            pass
        out.append({
            "device": f"{d.platform}:{d.id}",
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        })
    return out


def log_memory(tag: str) -> None:
    """Log per-device memory at a lifecycle point (cake/mod.rs:65-71)."""
    for s in device_memory_stats():
        used, peak, limit = (s["bytes_in_use"], s["peak_bytes_in_use"],
                             s["bytes_limit"])
        if used is None:
            log.info("[%s] %s: memory stats unavailable", tag, s["device"])
        else:
            log.info(
                "[%s] %s: %s in use (peak %s / limit %s)", tag, s["device"],
                human_bytes(used), human_bytes(peak or 0),
                human_bytes(limit or 0),
            )


@dataclass
class StepStats:
    """Windowed per-step throughput counters (worker.rs:254-283 analog).

    Call `step(bytes_in, bytes_out)` once per op; every `window` ops the
    moving-window ops/s + throughput is logged and returned.
    """

    name: str = "engine"
    window: int = NUM_OPS_TO_STATS
    ops: int = 0
    total_bytes_in: int = 0
    total_bytes_out: int = 0
    _win_start: float = field(default_factory=time.perf_counter)
    _win_bytes_in: int = 0
    _win_bytes_out: int = 0
    last_ops_per_s: float = 0.0

    def step(self, bytes_in: int = 0, bytes_out: int = 0) -> Optional[dict]:
        self.ops += 1
        self.total_bytes_in += bytes_in
        self.total_bytes_out += bytes_out
        self._win_bytes_in += bytes_in
        self._win_bytes_out += bytes_out
        if self.ops % self.window:
            return None
        now = time.perf_counter()
        dt = max(now - self._win_start, 1e-9)
        snap = {
            "ops_per_s": self.window / dt,
            "read_bytes_per_s": self._win_bytes_in / dt,
            "write_bytes_per_s": self._win_bytes_out / dt,
        }
        self.last_ops_per_s = snap["ops_per_s"]
        log.info(
            "%s: %.1f ops/s | read %s/s | write %s/s", self.name,
            snap["ops_per_s"], human_bytes(snap["read_bytes_per_s"]),
            human_bytes(snap["write_bytes_per_s"]),
        )
        self._win_start = now
        self._win_bytes_in = 0
        self._win_bytes_out = 0
        return snap
