"""Shared length-prefixed TCP framing for the coordination planes.

The control channel (serve/control.py) and the telemetry federation
plane (obs/federation.py) speak the same wire shape: a 4-byte
big-endian length prefix followed by a JSON payload, with a bounded
token-gated hello as the first message. This module is the ONE copy of
the pieces both sides share, so a fix to the framing or the bounded-
read discipline lands everywhere at once (the PR 8 mid-frame-timeout
fix needed two passes precisely because read paths had drifted apart).

Deliberately NOT shared: each consumer's streaming read loop. The
control client's persistent partial-frame buffer (timeout-resume
semantics), the control server's accept-deadline plumbing and the
collector's per-connection buffer genuinely differ — forcing them
through one abstraction would couple timeout behaviors that must stay
independent.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Optional

LEN = struct.Struct("!I")


def send_msg(sock: socket.socket, payload: bytes) -> None:
    """One length-prefixed message, written atomically enough for a
    stream socket (sendall)."""
    sock.sendall(LEN.pack(len(payload)) + payload)


def recv_bounded_msg(conn: socket.socket, max_len: int,
                     deadline: float) -> Optional[bytes]:
    """Read ONE length-prefixed message under an ABSOLUTE monotonic
    deadline and a payload-size cap; None on timeout, EOF, socket
    error, or a length outside (0, max_len].

    This is the hello-read discipline both planes use on their accept
    paths: the size cap stops an attacker-controlled multi-GiB length
    from allocating, and the absolute deadline stops byte-trickling
    from holding an accept/handler thread hostage (per-recv timeouts
    would multiply under trickling)."""
    buf = b""

    def fill(n: int) -> bool:
        nonlocal buf
        while len(buf) < n:
            rem = deadline - time.monotonic()
            if rem <= 0:
                return False
            conn.settimeout(rem)
            try:
                part = conn.recv(n - len(buf))
            except OSError:
                return False
            if not part:
                return False
            buf += part
        return True

    if not fill(LEN.size):
        return None
    (n,) = LEN.unpack(buf[:LEN.size])
    if not 0 < n <= max_len:
        return None
    if not fill(LEN.size + n):
        return None
    return buf[LEN.size:]
