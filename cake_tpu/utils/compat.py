"""JAX version compatibility shims.

The codebase targets the current `jax.shard_map` API (top-level export,
`check_vma=` kwarg). Older toolchains (<= 0.4.x) ship the same
functionality as `jax.experimental.shard_map.shard_map` with the kwarg
spelled `check_rep=`. Rather than pinning a minimum jax, install a
translating alias when the top-level name is missing — every
`jax.shard_map(...)` call site then works unchanged on both
generations. Imported for its side effect from `cake_tpu/__init__.py`.
"""

from __future__ import annotations

import jax


def install() -> None:
    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map as _sm
        except ImportError:  # pragma: no cover — no jax lacks both
            _sm = None
        if _sm is not None:
            def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                          **kw):
                if "check_vma" in kw:
                    kw["check_rep"] = kw.pop("check_vma")
                return _sm(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)

            jax.shard_map = shard_map

    try:
        from jax.experimental.pallas import tpu as pltpu
        if (not hasattr(pltpu, "CompilerParams")
                and hasattr(pltpu, "TPUCompilerParams")):
            # renamed upstream; alias so call sites use the new name
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:  # pragma: no cover — pallas-less builds
        pass

    if not hasattr(jax.lax, "axis_size"):
        # psum of the literal 1 over a named axis constant-folds to a
        # concrete Python int during tracing — the long-standing
        # pre-axis_size idiom, so `range(axis_size(...))` keeps working
        def axis_size(axis_name):
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size


install()
