"""Device selection and dtype policy.

Reference: `get_inference_device` probes cuda -> metal -> cpu
(utils/mod.rs:15-30) and the dtype parse defaults to f16 (cake/mod.rs:54-60).
On TPU the probe order is tpu -> cpu and the default compute dtype is
bfloat16 (the MXU-native type); f16 is honored if requested but bf16 is
strongly preferred on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_DTYPES = {
    "f16": jnp.float16,
    "bf16": jnp.bfloat16,
    "f32": jnp.float32,
}

# storage-only dtypes: valid for the KV cache (--kv-dtype), where values
# are written once and upcast into the attention matmul on read — halves
# KV HBM traffic/footprint — but not for weights/activations
_KV_DTYPES = {
    **_DTYPES,
    "f8_e4m3": jnp.float8_e4m3fn,
    "f8_e5m2": jnp.float8_e5m2,
}


def resolve_dtype(name: str):
    """Map a CLI dtype name to a jnp dtype (reference cake/mod.rs:54-60)."""
    try:
        return _DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unsupported dtype '{name}' (expected one of {sorted(_DTYPES)})"
        ) from None


def resolve_kv_dtype(name: str):
    """Map a --kv-dtype name (compute dtypes + fp8 storage variants)."""
    try:
        return _KV_DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unsupported kv dtype '{name}' "
            f"(expected one of {sorted(_KV_DTYPES)})"
        ) from None


def get_inference_device(cpu: bool = False, device_idx: int = 0):
    """Pick the inference device: TPU if present, else CPU.

    Mirrors the reference's availability probe (utils/mod.rs:15-30) with
    TPU in place of cuda/metal.
    """
    if cpu:
        return jax.devices("cpu")[device_idx]
    try:
        tpus = jax.devices("tpu")
        if tpus:
            return tpus[device_idx % len(tpus)]
    except RuntimeError:
        pass
    # Under the experimental axon platform, devices() may report a platform
    # name other than "tpu"; fall back to the default backend's devices.
    devs = jax.devices()
    if devs and devs[0].platform != "cpu":
        return devs[device_idx % len(devs)]
    return jax.devices("cpu")[device_idx]


def device_kind_summary() -> str:
    """Human-readable device inventory (WorkerInfo-style introspection).

    Replaces the reference's `WorkerInfo` message fields
    (proto/message.rs:42-58) with local JAX device/topology queries.
    """
    lines = []
    for d in jax.devices():
        lines.append(
            f"{d.id}: {d.platform}/{d.device_kind} process={d.process_index}"
        )
    return "\n".join(lines)
