"""Attention: grouped-query attention with softmax in f32.

Reference semantics (llama3/attention.rs:96-130): attention is computed with
f32 accumulation regardless of the model dtype, causal mask applied when
seq_len > 1, and GQA via `repeat_kv`. On TPU we keep q/k/v in the compute
dtype (bf16) and request f32 MXU accumulation via `preferred_element_type`
— numerically equivalent to the reference's explicit upcast, without the
extra HBM traffic. GQA is expressed with einsum over a grouped head axis so
no materialised `repeat_kv` copy is needed.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def gqa_attention(q, k, v, *, mask=None, scale: float | None = None):
    """Grouped-query attention over an arbitrary KV window.

    q:    [B, S, H,  hd]   (H = num attention heads)
    k,v:  [B, T, KV, hd]   (KV divides H; T >= S)
    mask: broadcastable to [B, H, S, T]; additive would be wasteful —
          boolean, True = attend.
    Returns [B, S, H, hd] in q.dtype.
    """
    B, S, H, hd = q.shape
    _, T, KV, _ = k.shape
    G = H // KV  # query heads per kv head
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    if k.dtype != q.dtype:
        # fp8 KV storage: upcast on read — XLA fuses the convert into the
        # dot, so HBM still streams the narrow dtype
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)

    qg = q.reshape(B, S, KV, G, hd)
    # scores: [B, KV, G, S, T] with f32 accumulation on the MXU
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    if mask is not None:
        m = mask
        if m.ndim == 4:  # [B, H, S, T] -> [B, KV, G, S, T]
            m = m.reshape(B, KV, G, S, T)
        elif m.ndim == 3:  # [B, S, T] per-row (ragged decode)
            m = m[:, None, None, :, :]
        elif m.ndim == 2:  # [S, T]
            m = m[None, None, None, :, :]
        scores = jnp.where(m, scores, jnp.float32(-1e30))
    probs = jax_softmax_f32(scores)
    out = jnp.einsum(
        "bkgst,btkd->bskgd", probs, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, S, H, hd).astype(q.dtype)


def jax_softmax_f32(scores):
    """Numerically-stable softmax in f32 (reference attention.rs:114)."""
    scores = scores.astype(jnp.float32)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - lax.stop_gradient(m))
    return e / jnp.sum(e, axis=-1, keepdims=True)


def causal_mask(seq_len: int, dtype=bool):
    """[S, S] lower-triangular causal mask (reference cache.rs:79-90)."""
    i = lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 0)
    j = lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 1)
    return (j <= i).astype(dtype)


def decode_mask(pos, seq_len: int, max_seq_len: int, window=None):
    """[S, T] mask for cached decode: query i (at absolute pos+i) may attend
    cache slots j <= pos+i. Static shapes; `pos` may be a traced scalar.

    window: sliding-window attention (Mistral-style) — additionally
    require kj > pos+i - window, so each query sees at most `window`
    most-recent positions (its own included)."""
    qi = lax.broadcasted_iota(jnp.int32, (seq_len, max_seq_len), 0)
    kj = lax.broadcasted_iota(jnp.int32, (seq_len, max_seq_len), 1)
    m = kj <= (qi + pos)
    if window is not None:
        m &= kj > (qi + pos - window)
    return m


def decode_mask_per_row(pos, max_seq_len: int, window=None):
    """[B, 1, T] mask for ragged single-token decode: row b (whose query sits
    at absolute position pos[b]) may attend cache slots j <= pos[b].
    window: see decode_mask."""
    kj = lax.broadcasted_iota(jnp.int32, (pos.shape[0], 1, max_seq_len), 2)
    m = kj <= pos[:, None, None]
    if window is not None:
        m &= kj > (pos[:, None, None] - window)
    return m
