"""Attention: grouped-query attention with softmax in f32.

Reference semantics (llama3/attention.rs:96-130): attention is computed with
f32 accumulation regardless of the model dtype, causal mask applied when
seq_len > 1, and GQA via `repeat_kv`. On TPU we keep q/k/v in the compute
dtype (bf16) and request f32 MXU accumulation via `preferred_element_type`
— numerically equivalent to the reference's explicit upcast, without the
extra HBM traffic. GQA is expressed with einsum over a grouped head axis so
no materialised `repeat_kv` copy is needed.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def gqa_attention(q, k, v, *, mask=None, scale: float | None = None):
    """Grouped-query attention over an arbitrary KV window.

    q:    [B, S, H,  hd]   (H = num attention heads)
    k,v:  [B, T, KV, hd]   (KV divides H; T >= S)
    mask: broadcastable to [B, H, S, T]; additive would be wasteful —
          boolean, True = attend.
    Returns [B, S, H, hd] in q.dtype.
    """
    B, S, H, hd = q.shape
    _, T, KV, _ = k.shape
    G = H // KV  # query heads per kv head
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    if k.dtype != q.dtype:
        # fp8 KV storage: upcast on read — XLA fuses the convert into the
        # dot, so HBM still streams the narrow dtype
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)

    qg = q.reshape(B, S, KV, G, hd)
    # scores: [B, KV, G, S, T] with f32 accumulation on the MXU
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    if mask is not None:
        m = mask
        if m.ndim == 4:  # [B, H, S, T] -> [B, KV, G, S, T]
            m = m.reshape(B, KV, G, S, T)
        elif m.ndim == 3:  # [B, S, T] per-row (ragged decode)
            m = m[:, None, None, :, :]
        elif m.ndim == 2:  # [S, T]
            m = m[None, None, None, :, :]
        scores = jnp.where(m, scores, jnp.float32(-1e30))
    probs = jax_softmax_f32(scores)
    out = jnp.einsum(
        "bkgst,btkd->bskgd", probs, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, S, H, hd).astype(q.dtype)


def jax_softmax_f32(scores):
    """Numerically-stable softmax in f32 (reference attention.rs:114)."""
    scores = scores.astype(jnp.float32)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - lax.stop_gradient(m))
    return e / jnp.sum(e, axis=-1, keepdims=True)


def causal_mask(seq_len: int, dtype=bool):
    """[S, S] lower-triangular causal mask (reference cache.rs:79-90)."""
    i = lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 0)
    j = lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 1)
    return (j <= i).astype(dtype)


def decode_mask(pos, seq_len: int, max_seq_len: int, window=None):
    """[S, T] mask for cached decode: query i (at absolute pos+i) may attend
    cache slots j <= pos+i. Static shapes; `pos` may be a traced scalar.

    window: sliding-window attention (Mistral-style) — additionally
    require kj > pos+i - window, so each query sees at most `window`
    most-recent positions (its own included)."""
    qi = lax.broadcasted_iota(jnp.int32, (seq_len, max_seq_len), 0)
    kj = lax.broadcasted_iota(jnp.int32, (seq_len, max_seq_len), 1)
    m = kj <= (qi + pos)
    if window is not None:
        m &= kj > (qi + pos - window)
    return m


def decode_mask_per_row(pos, max_seq_len: int, window=None):
    """[B, 1, T] mask for ragged single-token decode: row b (whose query sits
    at absolute position pos[b]) may attend cache slots j <= pos[b].
    window: see decode_mask."""
    kj = lax.broadcasted_iota(jnp.int32, (pos.shape[0], 1, max_seq_len), 2)
    m = kj <= pos[:, None, None]
    if window is not None:
        m &= kj > (pos[:, None, None] - window)
    return m


# -- ring-buffer (sliding-window) cache masks ---------------------------------
#
# A sliding-window model never attends further back than `window`, so the
# cache only needs W = min(window, max_seq) slots: position p lives in ring
# slot p % W (models/llama/cache.update_layer_cache_ring). The masks below
# translate "which absolute positions may query q attend" into ring-slot
# space. The reference keeps a dense cache and trims by concatenation
# (llama3/cache.rs:93-122); the ring drops KV memory to window/max_seq with
# zero copies per step.

def ring_decode_mask_per_row(pos, ring_len: int):
    """[B, 1, W] mask for ragged single-token decode over a ring cache.

    After this step's write, slot j holds absolute position
    p - ((p - j) mod W) — always within (p - W, p], i.e. inside any
    window >= W. So validity is purely "has slot j been written":
    j <= pos[b] (pre-wrap) or pos[b] >= W (every slot live)."""
    kj = lax.broadcasted_iota(jnp.int32, (pos.shape[0], 1, ring_len), 2)
    p = pos[:, None, None]
    return (kj <= p) | (p >= ring_len)


def uniform_forward_mask(pos, seq_len: int, ring_len_or_T: int, window,
                         ring: bool, n_real=None):
    """THE mask policy for uniform-position forwards, shared by
    model.forward and the pipelined forward_body so the single-device
    and pipelined attention semantics cannot drift: ring ->
    ring_concat_mask over [S, W+S]; dense -> decode_mask over [S, T]
    (optionally windowed)."""
    if ring:
        return ring_concat_mask(pos, seq_len, ring_len_or_T, window,
                                n_real=n_real)
    return decode_mask(pos, seq_len, ring_len_or_T, window=window)


def ring_concat_mask(pos, seq_len: int, ring_len: int, window: int,
                     n_real=None):
    """[S, W+S] mask for a prefill window of S <= W tokens at absolute
    positions pos..pos+S-1 attending concat(old ring, fresh window).

    The window's queries must see in-window history that the window's
    own ring write will overwrite (a full-W window replaces the entire
    ring), so ring prefill attends the PRE-write ring plus the fresh
    keys, and writes after (models/llama/model.block_forward ring path).

      * ring column j (< W): holds absolute a_j = pos-1 - ((pos-1-j)
        mod W) — the newest position < pos in that slot; valid iff
        a_j >= 0 (ever written) and a_j > pos+i - window.
      * fresh column W+jj: the window's token at absolute pos+jj;
        causal jj <= i (in-window by S <= W <= window). Junk columns
        jj >= n_real only reach padding queries i >= n_real, whose
        output the caller discards via last_idx."""
    del n_real  # junk freshness is handled by causality (see above)
    i_r = lax.broadcasted_iota(jnp.int32, (seq_len, ring_len), 0)
    j_r = lax.broadcasted_iota(jnp.int32, (seq_len, ring_len), 1)
    a_j = pos - 1 - jnp.mod(pos - 1 - j_r, ring_len)
    ring_ok = (a_j >= 0) & (a_j > pos + i_r - window)
    i_f = lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 0)
    jj = lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 1)
    fresh_ok = jj <= i_f
    return jnp.concatenate([ring_ok, fresh_ok], axis=1)
