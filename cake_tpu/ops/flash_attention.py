"""Pallas TPU flash attention (causal, GQA-aware) for the prefill path.

The reference computes attention as naive matmul→softmax→matmul in f32
(llama3/attention.rs:96-118), materialising the full [S, T] score matrix in
memory. On TPU that matrix is pure HBM traffic; the flash formulation keeps
one [block_q, block_k] tile in VMEM and carries online-softmax statistics
(m, l) across key blocks, so the kernel is MXU-bound instead of
bandwidth-bound for long sequences.

Layout: grid (batch, q_head, q_block, k_block); the k_block axis is the
innermost (sequential on TPU), carrying f32 accumulators in VMEM scratch.
GQA is handled in the k/v index maps (query head h reads kv head h // G) —
no repeat_kv materialisation. Causal blocks above the diagonal are skipped
with `pl.when` (upper-triangular tiles cost ~0).

CPU tests run the same kernel with interpret=True (tests/test_flash.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _online_softmax_step(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *,
                         scale: float, mask, v_valid=None):
    """One (q_block, k_block) tile of the online-softmax recurrence.

    mask: boolean [block_q, block_k] (True = attend) or None. Shared by
    the fresh-window and cache-aware kernels.
    v_valid: boolean [block_k, 1] or None — zero out v rows beyond the
    cache frontier before the p @ v matmul: a masked score contributes
    p = 0, but 0 * non-finite garbage is NaN, so garbage must never reach
    the dot.
    """
    q = q_ref[0, 0]                      # [block_q, hd]
    k = k_ref[0, 0]                      # [block_k, hd]
    v = v_ref[0, 0]
    if v_valid is not None:
        v = jnp.where(v_valid, v, 0.0)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                            # [block_q, block_k]
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)

    m_prev = m_ref[:, :1]                # [block_q, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)      # rescale of old accumulator
    p = jnp.exp(s - m_new)               # [block_q, block_k]
    l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)


def _finish_block(o_ref, acc_ref, l_ref):
    l = l_ref[:, :1]
    l = jnp.where(l == 0.0, 1.0, l)      # fully-masked row guard
    o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, block_q: int, block_k: int, causal: bool,
                  window=None):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = ik * block_k

    def compute():
        mask = None
        if causal:
            qi = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kj = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = kj <= qi
            if window is not None:
                # sliding-window attention: at most `window` most-recent
                # positions per query (own position included)
                mask &= kj > qi - window
        _online_softmax_step(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
                             scale=scale, mask=mask)

    if causal:
        # k_start/q_start are traced (grid ids), so gate at runtime;
        # with a window, key blocks entirely BELOW every query's window
        # are skipped too (the flash win windows exist for: out-of-window
        # tiles cost ~0)
        gate = k_start <= q_start + block_q - 1
        if window is not None:
            gate &= k_start + block_k - 1 > q_start - window

        @pl.when(gate)
        def _():
            compute()
    else:
        compute()

    @pl.when(ik == nk - 1)
    def _finish():
        _finish_block(o_ref, acc_ref, l_ref)


def _flash_kernel_cached(pos_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *,
                         scale: float, block_q: int, block_k: int,
                         seq_len: int, window=None):
    """Cache-aware variant: queries sit at absolute positions
    pos..pos+seq_len-1 and attend the whole KV cache [T], masked to
    kj <= pos + qi (chunked/continued prefill; pos is a prefetched
    scalar, so one compiled kernel serves every chunk position)."""
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)
    pos = pos_ref[0]

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = ik * block_k

    # skip key blocks entirely above this query block's last position
    # (their DMAs are also elided — the k/v index maps clamp to the same
    # limit, so Pallas re-reads the resident block instead of fetching);
    # with a window, blocks entirely below every query's window skip too
    gate = k_start <= pos + q_start + block_q - 1
    if window is not None:
        gate &= k_start + block_k - 1 > pos + q_start - window

    @pl.when(gate)
    def _():
        qi = pos + q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kj = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = kj <= qi
        if window is not None:
            mask &= kj > qi - window
        # cache slots at/after the write frontier pos+seq_len may hold
        # stale or non-finite garbage in the boundary block
        col_valid = (k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < pos + seq_len
        _online_softmax_step(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
                             scale=scale, mask=mask,
                             v_valid=col_valid)

    @pl.when(ik == nk - 1)
    def _finish():
        _finish_block(o_ref, acc_ref, l_ref)


def _flash_bhsd(q, k, v, *, scale, causal, block_q, block_k, interpret,
                window=None):
    """q [B,H,S,hd], k/v [B,KV,T,hd] -> [B,H,S,hd]."""
    B, H, S, hd = q.shape
    _, KV, T, _ = k.shape
    G = H // KV
    nq = S // block_q
    nk = T // block_k

    grid = (B, H, nq, nk)
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, hd),
                         lambda b, h, i, j: (b, h // G, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd),
                         lambda b, h, i, j: (b, h // G, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        # only the innermost (k) axis carries scratch state; the rest can be
        # scheduled across megacore
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def flash_attention(q, k, v, *, scale: float | None = None,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None,
                    window: int | None = None):
    """Flash attention over [B, S, H, hd] q and [B, T, KV, hd] k/v.

    Falls back to None-signalling (caller uses the einsum path) is NOT done
    here — callers should check `flash_supported(...)` first. Shapes must
    tile: S % block_q == 0, T % block_k == 0.
    """
    B, S, H, hd = q.shape
    _, T, KV, _ = k.shape
    if window is not None and not causal:
        raise ValueError(
            "window requires causal=True: the non-causal kernel applies "
            "no window mask, so the window would be silently ignored")
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    block_q = min(block_q, S)
    block_k = min(block_k, T)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qt = jnp.swapaxes(q, 1, 2)        # [B, H, S, hd]
    kt = jnp.swapaxes(k, 1, 2)        # [B, KV, T, hd]
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash_bhsd(qt, kt, vt, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, interpret=interpret,
                      window=window)
    return jnp.swapaxes(out, 1, 2)


def _flash_bhsd_cached(pos, q, k, v, *, scale, block_q, block_k,
                       interpret, window=None):
    """q [B,H,S,hd] at absolute offset pos; k/v [B,KV,T,hd] full cache."""
    B, H, S, hd = q.shape
    _, KV, T, _ = k.shape
    G = H // KV
    grid = (B, H, S // block_q, T // block_k)
    kernel = functools.partial(
        _flash_kernel_cached, scale=scale, block_q=block_q, block_k=block_k,
        seq_len=S, window=window,
    )

    def kv_index(b, h, i, j, pos_ref):
        # clamp skipped k-blocks (beyond this q-block's causal limit) to
        # the limit block: Pallas elides the DMA when the index repeats,
        # so a pos=0 whole-cache call reads only the live prefix, not all
        # T slots. With a window, blocks entirely BELOW every query's
        # window clamp to the lowest in-window block — at long context
        # this is most of the cache, and flash there is bandwidth-bound,
        # so eliding these DMAs is the point of the window.
        limit = jax.lax.div(pos_ref[0] + i * block_q + block_q - 1,
                            jnp.int32(block_k))
        j = jnp.minimum(j, limit)
        if window is not None:
            lo = jax.lax.div(
                jnp.maximum(pos_ref[0] + i * block_q - window + 1,
                            jnp.int32(0)),
                jnp.int32(block_k))
            j = jnp.maximum(j, lo)
        return (b, h // G, j, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd),
                         lambda b, h, i, j, *_: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, hd), kv_index),
            pl.BlockSpec((1, 1, block_k, hd), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd),
                               lambda b, h, i, j, *_: (b, h, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, k, v)


def flash_attention_cached(q, k_cache, v_cache, pos, *,
                           scale: float | None = None, block_q: int = 128,
                           block_k: int = 128,
                           interpret: bool | None = None,
                           window: int | None = None):
    """Flash attention for a query window at absolute position `pos`
    against the full KV cache (chunked/continued prefill, pos > 0).

    q:              [B, S, H, hd] — the chunk's queries (RoPE applied)
    k_cache/v_cache:[B, T, KV, hd] — entries < pos+S written (the chunk's
                    own k/v included); later slots may be garbage, they
                    are causally masked.
    pos:            traced scalar — one compiled kernel serves every chunk.
    Equivalent to gqa_attention(q, kc, vc, mask=decode_mask(pos, S, T)).
    """
    B, S, H, hd = q.shape
    _, T, KV, _ = k_cache.shape
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    block_q = min(block_q, S)
    block_k = min(block_k, T)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k_cache, 1, 2)
    vt = jnp.swapaxes(v_cache, 1, 2)
    out = _flash_bhsd_cached(pos, qt, kt, vt, scale=scale,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret, window=window)
    return jnp.swapaxes(out, 1, 2)


def flash_supported(S: int, T: int, H: int, KV: int, hd: int,
                    block_q: int = 128, block_k: int = 128) -> bool:
    """Static shape check for the flash path (S = query window, T = KV
    length — equal for fresh-prompt prefill, T > S for the cache-aware
    chunked-prefill kernel).

    Beyond divisibility, the clamped blocks must be Mosaic-tileable: the
    second-minor dim of a bf16 tile is 16, so unaligned blocks (e.g. S=100
    -> block_q=100) compile only in interpret mode and must fall back to
    the einsum path on hardware. The minor (lane) dim is the head dim:
    on real TPU it must fill 128-wide lanes, or Mosaic rejects the
    kernel (found running the tiny-shape suite on silicon: hd=16
    compiles in interpret mode, HTTP-500s out of the hardware compiler).
    Callers that know the head dim pass it; production configs (hd=128)
    pass the gate, tiny test configs fall back to the einsum path on
    hardware and keep exercising the kernel in interpret mode on CPU.
    """
    bq = min(block_q, S)
    bk = min(block_k, T)
    if hd % 128 != 0 and jax.default_backend() == "tpu":
        return False
    return (S > 1 and S % bq == 0 and T % bk == 0 and H % KV == 0
            and bq % 16 == 0 and bk % 16 == 0)
