"""Sparse mixture-of-experts FFN (Mixtral-style) for decoder blocks.

The reference is dense-only (`mlp.rs:7-11` — SURVEY.md §2.6 lists expert
parallelism as absent); this is a capability extension. Design is
TPU-first:

  * Routing is `lax.top_k` over router logits with softmax renormalised
    over the selected experts (Mixtral semantics), producing a dense
    [tokens, E] combine matrix — static shapes, no sorting/scatter, so the
    whole thing jits and scans.
  * Expert computation is batched einsum over the (possibly EP-sharded)
    expert axis: every expert runs on every token and the combine matrix
    zeroes the non-selected ones. For inference-sized token counts this
    keeps the MXU busy with one big contraction instead of ragged gathers;
    XLA shards the expert axis when the weights carry an `ep`
    PartitionSpec.
  * Under `shard_map` (the manual pipeline path), pass `ep_axis`: each
    shard holds an [E/ep, ...] slice of the expert weights, computes its
    local experts against its slice of the combine matrix, and `psum`s the
    partial outputs over the axis — token dispatch rides ICI as a single
    reduction instead of an all-to-all.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from cake_tpu.ops.quant import qeinsum


def route_top_k(x, router_w, k: int):
    """Top-k routing combine matrix.

    x:        [N, D] tokens
    router_w: [D, E] router weights
    returns   [N, E] float32: softmax weight for each selected expert,
              zero elsewhere. Softmax is over the top-k logits only
              (Mixtral renormalisation).
    """
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)  # [N, E]
    E = logits.shape[-1]
    top_vals, top_idx = lax.top_k(logits, k)                       # [N, k]
    weights = jax.nn.softmax(top_vals, axis=-1)                    # [N, k]
    onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)         # [N, k, E]
    return jnp.einsum("nk,nke->ne", weights, onehot)


def moe_mlp(lp, h, num_experts_per_tok: int,
            ep_axis: Optional[str] = None):
    """Sparse SwiGLU FFN over experts.

    lp leaves: router [D, E]; we_gate/we_up [E_local, D, F];
    we_down [E_local, F, D]. E_local == E except under shard_map EP, where
    each shard holds its contiguous slice and `ep_axis` names the mesh axis.
    Returns the *unreduced-over-tp* output: when F is additionally
    Megatron-sharded the caller (block_skeleton) psums over tp, exactly as
    for the dense path — EP and TP reductions compose.
    """
    B, S, D = h.shape
    x = h.reshape(B * S, D)
    combine = route_top_k(x, lp["router"], num_experts_per_tok)    # [N, E]

    e_local = lp["we_gate"].shape[0]
    if ep_axis is not None:
        offset = lax.axis_index(ep_axis) * e_local
        combine = lax.dynamic_slice_in_dim(combine, offset, e_local, axis=1)

    # [N, E_local, F]: all (local) experts on all tokens; combine masks.
    gate = qeinsum("nd,edf->nef", x, lp["we_gate"])
    up = qeinsum("nd,edf->nef", x, lp["we_up"])
    act = jax.nn.silu(gate) * up
    per_expert = qeinsum("nef,efd->ned", act, lp["we_down"])       # [N, E, D]
    out = jnp.einsum("ned,ne->nd", per_expert,
                     combine.astype(per_expert.dtype))
    if ep_axis is not None:
        out = lax.psum(out, ep_axis)
    return out.reshape(B, S, D).astype(h.dtype)
