"""Pallas TPU matmul over nibble-packed int4 weights (decode hot path).

Why a kernel: batch-1 decode streams the whole weight set per token, so
tok/s == HBM bandwidth / weight bytes (SURVEY.md §6). int4 storage halves
int8's traffic, but XLA cannot consume packed nibbles: the S4 dtype cannot
cross a jit boundary on this backend, and an unpack-then-dot graph
materialises the dequantized copy in HBM — costing MORE traffic than int8.
This kernel reads the packed bytes into VMEM, sign-extends the nibbles in
registers, and runs the two half-dots per group tile; dequantized weights
never exist in HBM. The reference has no quantization at all (f16 floor,
cake/mod.rs:54-60).

Storage layout ("group-halves", produced by ops.quant.quantize_group):
a weight [In, Out] is grouped into G = In/g row groups; within group gi,
input row j (j < g/2) packs into the LOW nibble and row j + g/2 into the
HIGH nibble of packed byte [gi*g/2 + j, out]. Both nibble-mates share the
group's scale row, so a tile's two dots are scaled by one [1, block_out]
row, and the kernel slices x contiguously (x_group[:, :g/2] / [g/2:]) —
no strided loads. Scales are f32 [G, Out].

The kernel is matvec-shaped (M <= MAX_KERNEL_M rows): decode batches pad
M up to a sublane multiple and the grid streams (Out/block_out, G) tiles
with the group axis innermost, accumulating in an f32 VMEM scratch.
Prefill (large M) takes the XLA dequantize path instead — it is
MXU-bound there, and the per-layer dequantized copy is amortised by the
[S, In] @ [In, Out] compute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# decode/matvec shapes only; larger M falls back to the dequantize path
MAX_KERNEL_M = 64


def pack_int4(q: jnp.ndarray, g: int) -> jnp.ndarray:
    """Pack int4 values (int8 array in [-8, 7], contract dim -2) into
    uint8 bytes using the group-halves layout, BIASED by +8 (nibbles store
    v+8 in [0, 15]). The bias lets the kernel unpack with one mask/shift
    per nibble instead of a sign-extending double-shift — the unpack is
    VPU-bound and sets the kernel's speed — while the dot's bias
    contribution folds into a per-group sum(x) correction.
    [.., In, Out] -> [.., In/2, Out]."""
    *lead, In, Out = q.shape
    assert In % g == 0 and g % 2 == 0, (In, g)
    G = In // g
    v = (q.astype(jnp.int32) + 8) & 0xF
    v = v.reshape(*lead, G, g, Out)
    lo = v[..., : g // 2, :]
    hi = v[..., g // 2:, :]
    packed = lo | (hi << 4)
    return packed.astype(jnp.uint8).reshape(*lead, In // 2, Out)


def unpack_int4(packed: jnp.ndarray, g: int) -> jnp.ndarray:
    """Inverse of pack_int4: [.., In/2, Out] uint8 -> [.., In, Out] int8
    (true signed int4 values; the storage bias is removed)."""
    *lead, half, Out = packed.shape
    G = half // (g // 2)
    p = packed.astype(jnp.int32).reshape(*lead, G, g // 2, Out)
    lo = (p & 0xF) - 8
    hi = (p >> 4) - 8
    w = jnp.concatenate([lo, hi], axis=-2)          # [.., G, g, Out]
    return w.astype(jnp.int8).reshape(*lead, G * g, Out)


def _int4_kernel(x_ref, p_ref, s_ref, o_ref, acc_ref, *, g: int, K: int):
    """One (out_block, group_block) tile: K groups' packed bytes resident,
    per group unpack→concat→one [M, g] x [g, bo] dot, scale, accumulate.

    K groups per grid step keeps each packed DMA block large (hundreds of
    KiB) — a one-group grid fragments the weight stream into tiny
    transfers and loses most of the HBM bandwidth to per-step overhead
    (measured 4x slower on an 8B walk)."""
    gi = pl.program_id(1)

    @pl.when(gi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    h = g // 2
    for k in range(K):
        p32 = p_ref[k * h:(k + 1) * h, :].astype(jnp.int32)  # [g/2, bo]
        # nibbles store v+8: one mask/shift each (the unpack is the VPU
        # bottleneck); the +8 bias is removed AFTER the dots via the
        # group's sum(x) — dot(x, w+8) == dot(x, w) + 8*sum(x)
        lo = (p32 & 0xF).astype(x_ref.dtype)
        hi = (p32 >> 4).astype(x_ref.dtype)
        xg = x_ref[:, k * g:(k + 1) * g]                     # [M, g]
        part = jax.lax.dot_general(
            xg[:, :h], lo, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        part = part + jax.lax.dot_general(
            xg[:, h:], hi, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        xsum = jnp.sum(xg.astype(jnp.float32), axis=1, keepdims=True)
        acc_ref[:] += (part - 8.0 * xsum) * s_ref[k, 0]

    @pl.when(gi == pl.num_programs(1) - 1)
    def _finish():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _pick_block_out(out: int) -> int:
    for b in (1024, 512, 256, 128):
        if out % b == 0:
            return b
    return 0


def _pick_k_groups(n_groups: int, g: int) -> int:
    """Groups per grid step: target ~512 packed rows per block."""
    k = max(1, min(n_groups, 1024 // g))
    while k > 1 and n_groups % k:
        k -= 1
    return k


def kernel_supported(m: int, in_dim: int, g: int, out: int) -> bool:
    if in_dim % 128 != 0 and jax.default_backend() == "tpu":
        # the x block's minor (lane) dim is in_dim: sub-128 lanes
        # compile in interpret mode but Mosaic rejects them on real
        # silicon (found running the tiny-shape suite on chip) — fall
        # back to the dequant-matmul path there
        return False
    return (m <= MAX_KERNEL_M and in_dim % g == 0 and g % 2 == 0
            and (g // 2) % 8 == 0 and _pick_block_out(out) > 0)


@functools.partial(jax.jit, static_argnames=("g", "interpret"))
def int4_matmul(x: jnp.ndarray, packed: jnp.ndarray, scale: jnp.ndarray,
                *, g: int, interpret: bool | None = None) -> jnp.ndarray:
    """x [M, In] @ packed-int4 [In/2, Out] with group scales [G, Out].

    Callers must check kernel_supported(...) first. M is padded to a
    sublane multiple internally; returns [M, Out] in x.dtype.
    """
    M, In = x.shape
    half, Out = packed.shape
    G = scale.shape[0]
    assert In == 2 * half and G * g == In, (x.shape, packed.shape, g)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_out = _pick_block_out(Out)
    K = _pick_k_groups(G, g)
    Mp = max(8, -(-M // 8) * 8)
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_int4_kernel, g=g, K=K),
        grid=(Out // block_out, G // K),
        in_specs=[
            pl.BlockSpec((Mp, K * g), lambda io, gi: (0, gi)),
            pl.BlockSpec((K * (g // 2), block_out), lambda io, gi: (gi, io)),
            # scale as [G, 1, Out]: a (K, 1, block_out) block keeps the
            # last-two block dims TPU-legal (dim -2 equals the array dim)
            pl.BlockSpec((K, 1, block_out), lambda io, gi: (gi, 0, io)),
        ],
        out_specs=pl.BlockSpec((Mp, block_out), lambda io, gi: (0, io)),
        out_shape=jax.ShapeDtypeStruct((Mp, Out), x.dtype),
        scratch_shapes=[pltpu.VMEM((Mp, block_out), jnp.float32)],
        interpret=interpret,
    )(x, packed, scale[:, None, :])
    return out[:M]
