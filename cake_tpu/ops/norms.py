"""Normalisation ops.

Reference: candle_nn RmsNorm used by each decoder block and the final norm
(transformer.rs:35-41, llama.rs:195-199). Computed in f32 and cast back to
the compute dtype, matching candle's rms_norm semantics.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x, weight, eps: float = 1e-5):
    """RMSNorm: x * rsqrt(mean(x^2) + eps) * weight, reduced in f32."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)
