"""Rotary position embeddings.

Reference semantics: cos/sin tables precomputed for every position up to the
max sequence length (llama3/cache.rs:23-61: inv_freq = theta^(-2i/d), outer
product with positions) and applied per attention call via candle's
`rotary_emb::rope` (attention.rs:25-35), which is the non-interleaved
("rotate-half" / NeoX / HF-Llama) variant.

On TPU the tables live in HBM once per process; `apply_rope` gathers the
rows for the current positions with a dynamic slice (static shapes, no
recompute per step).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def precompute_rope(head_dim: int, max_seq_len: int, theta: float = 10000.0,
                    dtype=jnp.float32):
    """(cos, sin) tables of shape [max_seq_len, head_dim//2]."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [S, hd/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def rope_rows(cos, sin, pos, seq_len: int):
    """Slice [pos : pos+seq_len] rows out of the tables (pos may be traced)."""
    c = lax.dynamic_slice_in_dim(cos, pos, seq_len, axis=0)
    s = lax.dynamic_slice_in_dim(sin, pos, seq_len, axis=0)
    return c, s


def rope_rows_per_row(cos, sin, pos):
    """Gather one table row per batch element (ragged decode).

    pos: [B] absolute positions -> (cos, sin) of shape [B, 1, head_dim//2],
    ready for `apply_rope` in per-row mode.
    """
    c = jnp.take(cos, pos, axis=0)[:, None, :]
    s = jnp.take(sin, pos, axis=0)[:, None, :]
    return c, s


def apply_rope(x, cos, sin):
    """Rotate-half RoPE.

    x:        [batch, seq, heads, head_dim]
    cos/sin:  [seq, head_dim//2] shared across the batch, or
              [batch, seq, head_dim//2] per-row (ragged decode).
    """
    half = x.shape[-1] // 2
    x1 = x[..., :half]
    x2 = x[..., half:]
    if cos.ndim == 2:
        c = cos[None, :, None, :].astype(jnp.float32)
        s = sin[None, :, None, :].astype(jnp.float32)
    else:
        c = cos[:, :, None, :].astype(jnp.float32)
        s = sin[:, :, None, :].astype(jnp.float32)
    x1f = x1.astype(jnp.float32)
    x2f = x2.astype(jnp.float32)
    out = jnp.concatenate([x1f * c - x2f * s, x2f * c + x1f * s], axis=-1)
    return out.astype(x.dtype)
